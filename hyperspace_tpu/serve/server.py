"""Asyncio HTTP/1.1 front door over the collator (stdlib only).

The event loop ROADMAP item 2 asked for: concurrent HTTP requests in,
the continuous-batching collator (``serve/collator.py``) between them
and the engine, the PR 9 overload machinery enforced per request, and
the PR 7 latency histograms measuring it all.  Hand-rolled HTTP/1.1
JSON handling on ``asyncio`` streams — no web framework, no new
dependencies; the protocol surface is four routes (docs/serving.md
"HTTP front door"):

====================  ======================================================
route                 body / answer
====================  ======================================================
``POST /v1/topk``     ``{"ids": [...], "k": 5, "exclude_self"?: bool,
                      "deadline_ms"?: ms}`` → ``{"neighbors": [[...]],
                      "dists": [[...]]}``
``POST /v1/score``    ``{"u": [...], "v": [...], "prob"?: bool, "fd_r"?,
                      "fd_t"?, "deadline_ms"?}`` → ``{"scores": [...]}``
``POST /v1/upsert``   ``{"ids": [...], "rows": [[...]], "deadline_ms"?}``
                      → ``{"upserted", "inserted", "generation",
                      "segment_rows"}`` (live engines —
                      serve/delta.py; frozen engines answer 400)
``POST /v1/delete``   ``{"ids": [...], "deadline_ms"?}`` →
                      ``{"deleted", "generation"}``
``POST /admin/rollover``  ``{"target": "<artifact path>"}`` → the flip
                      report (serve/rollover.py) — 400 when no
                      rollover coordinator is armed or the gate
                      refuses; the old stack keeps serving either way
``GET|POST /v1/stats``  ``batcher.stats()`` + a ``server`` block
                      (served/inflight/draining) + ``recompiles`` +
                      the windowed SLO block when a window is armed
``GET /healthz``      liveness + identity JSON: ok/draining, uptime_s,
                      package version, artifact fingerprint, engine
                      scan_signature, precision lane, degrade level
                      (503 + ``ok: false`` once draining)
``GET /metrics``      Prometheus text exposition of the registry
                      (telemetry/exposition.py) — counters, gauges,
                      histograms with cumulative buckets
====================  ======================================================

**Request tracing**: every parsed request gets a request id
(``X-Request-Id`` accepted from the client, sanitized; generated
otherwise), echoed as a response header, threaded through the collator
into the lifecycle (span args, collator flush id) and the structured
JSONL access log when one is armed (``access_log=`` —
serve/access.py).

Failed requests answer the SAME typed body as the stdin loop
(``{"error": {"kind": ..., "message": ...}}`` — docs/serving.md "Error
taxonomy") with the kind mapped onto the status code: ``parse``/
``validation`` → 400, ``overloaded`` → **429**, ``deadline_exceeded`` →
**504**, ``internal`` → 500.  Exactly one response per request; a
malformed request never takes the connection pool down.

**Deadline propagation starts at socket accept**: the lifecycle's
``t_enq`` is stamped when the request line arrives on the socket, so
time spent queued in the collator (and in the dispatch executor) counts
against the request's ``deadline_ms`` — a 504 can be shed while queued,
before any device work (the batcher's "never dispatched late" rule,
now with real queueing in front of it).

**Drain** mirrors the stdin loop's SIGTERM contract: stop accepting
(listeners closed — new connections are refused at the socket),
force-flush the collator's pending buckets, answer every in-flight
request, close keep-alive connections (idle ones immediately — a
silent client cannot block shutdown), and emit the latency summary.

Concurrency model: one task per connection; requests on one connection
are sequential (HTTP/1.1 without pipelining), concurrency comes from
connections.  All blocking work (device dispatch) lives on the
collator's single dispatch executor — nothing here blocks the loop, and
the ``blocking-call-in-async`` hyperlint rule keeps it that way.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import signal
import sys
import time
import urllib.parse
from typing import Optional

import numpy as np

import hyperspace_tpu
from hyperspace_tpu.serve.access import new_request_id
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.collator import DEFAULT_MAX_WAIT_US, Collator
from hyperspace_tpu.serve.errors import ServeError, error_response
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans
from hyperspace_tpu.telemetry.exposition import render_prometheus

MAX_BODY_BYTES = 8 << 20  # one request's JSON; far past any bucket
MAX_HEADERS = 128         # header-count cap: no unbounded dict growth
_STATUS_BY_KIND = {"parse": 400, "validation": 400, "overloaded": 429,
                   "deadline_exceeded": 504, "unknown_tenant": 404,
                   "internal": 500}
_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 413: "Payload Too Large",
            429: "Too Many Requests", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout"}


def _json_default(o):
    """numpy scalars/arrays degrade per-value (the bench emit rule)."""
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def _json_bool(req: dict, key: str, default: bool) -> bool:
    """Strict JSON boolean — the string \"false\" must be an error, not
    truthy (the stdin loop's reject-don't-coerce policy)."""
    v = req.get(key, default)
    if not isinstance(v, bool):
        raise ValueError(
            f"{key} must be a JSON boolean, got {type(v).__name__}")
    return v


def _req_deadline(req: dict) -> Optional[float]:
    """The optional per-request ``deadline_ms`` field, strict: a
    positive JSON number, not a bool/string; None = server default."""
    v = req.get("deadline_ms")
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0:
        raise ValueError(
            f"deadline_ms must be a positive number, got {v!r}")
    return float(v)


def _req_number(req: dict, key: str, default: float) -> float:
    v = req.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ValueError(f"{key} must be a JSON number, got {v!r}")
    return float(v)


class _TextPayload(str):
    """A non-JSON response body (the ``/metrics`` exposition): written
    verbatim with the given content type instead of json.dumps."""

    content_type = "text/plain; version=0.0.4; charset=utf-8"


class _Request:
    __slots__ = ("method", "target", "headers", "body", "t_in", "close",
                 "request_id")

    def __init__(self, method, target, headers, body, t_in, close):
        self.method = method
        self.target = target
        self.headers = headers
        self.body = body
        self.t_in = t_in       # socket-in stamp: deadline origin
        self.close = close     # client asked Connection: close / HTTP/1.0
        # accept-or-generate (docs/observability.md "Request tracing"):
        # the client's X-Request-Id wins; otherwise a fresh id — either
        # way it is echoed back and stamped on the access-log line.
        # Sanitized to [A-Za-z0-9._-] and capped: the id is echoed into
        # a response HEADER, so a hostile value must not be able to
        # smuggle CR/LF (header injection) or megabytes
        rid = headers.get("x-request-id", "")
        # ASCII-explicit: str.isalnum alone admits latin-1 letters
        # ('µ'), which would ride the echoed header as non-ASCII bytes
        rid = "".join(c for c in rid
                      if c.isascii() and (c.isalnum() or c in "-_."))[:64]
        self.request_id = rid or new_request_id()


class _BadRequest(Exception):
    """Protocol-level failure (not a serve op): answered 400 + close."""


class _TooLarge(_BadRequest):
    """Body past MAX_BODY_BYTES: answered 413 + close."""


class HttpFrontDoor:
    """The asyncio HTTP server (module docstring).  Lifecycle:
    ``await start()`` binds (port 0 = ephemeral; ``.port`` holds the
    bound port), ``await serve_until_drained()`` installs the SIGTERM
    handler and blocks until a drain completes, or drive ``drain()``
    directly (tests, embedded use)."""

    def __init__(self, batcher: Optional[RequestBatcher] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 max_wait_us: float = DEFAULT_MAX_WAIT_US,
                 collator: Optional[Collator] = None,
                 registry=None):
        # multi-tenant mode (serve/registry.py): the EngineRegistry
        # owns every stack; `batcher`/`collator` become views onto the
        # DEFAULT tenant's (property below), so single-tenant callers —
        # the rollover coordinator included — keep working unchanged
        self._registry = registry
        if registry is not None:
            if batcher is not None or collator is not None:
                raise ValueError(
                    "registry= and batcher=/collator= are mutually "
                    "exclusive — the registry owns the tenant stacks")
        else:
            if batcher is None:
                raise ValueError("HttpFrontDoor needs a batcher "
                                 "or a registry")
            self._batcher = batcher
            self._collator = collator or Collator(
                batcher, max_wait_us=max_wait_us)
        # blue-green flips (serve/rollover.py): armed by the CLI /
        # embedder AFTER construction (the coordinator needs the door);
        # None = /admin/rollover answers 400
        self.rollover: Optional[object] = None
        self.host = host
        self.port = int(port)
        self.served = 0          # responses written (errors included)
        self.inflight = 0        # requests currently being handled
        self.aborted_connections = 0  # abandoned at the drain timeout
        self.t_start = time.monotonic()  # healthz uptime origin
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()
        self._draining: Optional[asyncio.Event] = None
        self._drained: Optional[asyncio.Event] = None

    # --- default-tenant views -------------------------------------------------
    # With a registry armed, `door.batcher` / `door.collator` read AND
    # write the default tenant's stack — the rollover coordinator's
    # atomic flip (`door.batcher = standby`) keeps flipping the default
    # tenant, and every single-tenant code path stays source-compatible.

    @property
    def batcher(self) -> RequestBatcher:
        if self._registry is not None:
            return self._registry.default.batcher
        return self._batcher

    @batcher.setter
    def batcher(self, b: RequestBatcher) -> None:
        if self._registry is not None:
            self._registry.default.batcher = b
        else:
            self._batcher = b

    @property
    def collator(self) -> Collator:
        if self._registry is not None:
            return self._registry.default.collator
        return self._collator

    @collator.setter
    def collator(self, c: Collator) -> None:
        if self._registry is not None:
            self._registry.default.collator = c
        else:
            self._collator = c

    @property
    def registry(self):
        return self._registry

    # --- lifecycle ------------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._draining = asyncio.Event()
        self._drained = asyncio.Event()
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def serve_until_drained(self) -> None:
        """Install SIGTERM → drain (where signal handlers can install)
        and block until the drain finishes."""
        loop = asyncio.get_running_loop()
        installed = False
        try:
            loop.add_signal_handler(
                signal.SIGTERM,
                lambda: asyncio.ensure_future(self.drain()))
            installed = True
        except (NotImplementedError, RuntimeError, ValueError):
            pass  # non-main thread / platform without signal support
        try:
            await self._drained.wait()
        finally:
            if installed:
                loop.remove_signal_handler(signal.SIGTERM)

    async def drain(self) -> None:
        """Graceful shutdown: refuse new connections, flush pending
        collator buckets, answer in-flight requests, close idle
        connections, release the dispatch executor.  Idempotent."""
        if self._draining.is_set():
            await self._drained.wait()
            return
        self._draining.set()
        self._server.close()
        await self._server.wait_closed()
        # queued batches must not wait out their max-wait timers while
        # the listeners are already closed — every tenant's
        if self._registry is not None:
            for stack in self._registry.tenants():
                stack.collator.flush_all()
        else:
            self.collator.flush_all()
        if self._conn_tasks:
            # in-flight requests answer; idle keep-alive readers cancel
            # immediately (the read/drain race in _on_connection).
            # Connections STILL pending at the timeout are abandoned —
            # counted, never silently claimed as drained
            _done, pending = await asyncio.wait(self._conn_tasks,
                                                timeout=30.0)
            self.aborted_connections = len(pending)
        # wait=False: a still-running device dispatch must not block the
        # event loop from inside this async def (the blocking-call
        # hazard this PR's own lint rule polices) — the executor thread
        # finishes on its own and is joined at interpreter exit
        if self._registry is not None:
            self._registry.close(wait=False)
        else:
            self.collator.close(wait=False)
        if self.batcher.recorder is not None:
            # SIGTERM/drain is a flight-recorder trigger: the last
            # requests before shutdown are exactly the evidence a
            # rollback post-mortem wants (docs/observability.md)
            # wait=True: the process is about to exit — the evidence
            # must be on disk before the drain completes
            self.batcher.recorder.dump("sigterm_drain", _cls="drain",
                                       wait=True)
        self._drained.set()

    @property
    def draining(self) -> bool:
        return self._draining is not None and self._draining.is_set()

    # --- connection handling --------------------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        try:
            while not self._draining.is_set():
                read = asyncio.ensure_future(self._read_request(reader))
                drainw = asyncio.ensure_future(self._draining.wait())
                # race the next request against drain: a SIGTERM while
                # this connection idles must not wait for the client's
                # next request (the stdin loop's select-poll analog,
                # event-driven instead of polled)
                done, _ = await asyncio.wait(
                    {read, drainw},
                    return_when=asyncio.FIRST_COMPLETED)
                drainw.cancel()
                if read not in done:
                    read.cancel()
                    with contextlib.suppress(
                            asyncio.CancelledError, Exception):
                        await read  # join the cancelled read
                    break
                try:
                    req = read.result()
                except _TooLarge as e:
                    # framing failures feed the same error accounting
                    # as body-level ones: a storm of oversized/garbled
                    # HTTP must tick serve/errors, the window's error
                    # rate, and the flight recorder's burst detector
                    self._framing_access("validation")
                    await self._write_response(
                        writer, 413,
                        {"error": {"kind": "validation",
                                   "message": str(e)}},
                        close=True)
                    break
                except _BadRequest as e:
                    self._framing_access("parse")
                    await self._write_response(
                        writer, 400,
                        {"error": {"kind": "parse", "message": str(e)}},
                        close=True)
                    break
                except (ConnectionError, asyncio.IncompleteReadError):
                    break  # peer went away mid-request
                if req is None:
                    break  # clean EOF between requests
                self.inflight += 1
                try:
                    status, payload = await self._route(req)
                finally:
                    self.inflight -= 1
                close = req.close or self._draining.is_set()
                await self._write_response(writer, status, payload,
                                           close=close,
                                           request_id=req.request_id)
                if close:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # peer reset under our feet: nothing left to answer
        finally:
            self._conn_tasks.discard(task)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    @staticmethod
    async def _read_line(reader) -> bytes:
        """One protocol line; a line past the StreamReader's buffer
        limit (64 KiB default) surfaces as ValueError — mapped onto
        the 400 path, never an unhandled task death (the 'exactly one
        response per request' contract covers hostile lines too)."""
        try:
            return await reader.readline()
        except ValueError as e:  # LimitOverrunError → ValueError
            raise _BadRequest(f"protocol line too long ({e})") from None

    async def _read_request(self, reader) -> Optional[_Request]:
        line = await self._read_line(reader)
        if not line:
            return None
        t_in = time.perf_counter()  # socket-in: the deadline origin
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/"):
            raise _BadRequest(f"malformed request line: {line[:80]!r}")
        method, target, version = parts
        headers = {}
        while True:
            h = await self._read_line(reader)
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= MAX_HEADERS:
                # a protocol-level failure, not an oversized payload:
                # 400, like any other unparseable-request shape
                raise _BadRequest(f"more than {MAX_HEADERS} headers")
            name, sep, val = h.decode("latin-1").partition(":")
            if sep:
                headers[name.strip().lower()] = val.strip()
        body = b""
        cl = headers.get("content-length")
        if cl is not None:
            try:
                n = int(cl)
            except ValueError:
                raise _BadRequest(
                    f"bad Content-Length: {cl!r}") from None
            if n < 0:
                raise _BadRequest(f"negative Content-Length {n}")
            if n > MAX_BODY_BYTES:
                raise _TooLarge(
                    f"Content-Length {n} > {MAX_BODY_BYTES} cap")
            if n:
                body = await reader.readexactly(n)
        close = (headers.get("connection", "").lower() == "close"
                 or version == "HTTP/1.0")
        return _Request(method, target, headers, body, t_in, close)

    # --- routing --------------------------------------------------------------

    def _framing_access(self, outcome: str) -> None:
        """Error-account an HTTP framing failure (bad request line,
        over-limit headers, oversized body) — no parsed request
        exists, so the record carries a generated id and the ``none``
        route, but the counters/window/recorder still see the storm."""
        self.batcher.emit_synthetic_access("none", outcome=outcome)

    def _serve_access(self, req: _Request, route: str,
                      outcome: str) -> None:
        """Access-log a serve-op failure that never reached the
        collator (body parse, pre-dispatch validation) — the collator
        and batcher emit for everything past their entry, so this
        covers exactly the complement (no double lines).  Scrape/admin
        routes (healthz/stats/metrics) are deliberately not logged:
        a 15 s scrape cadence would drown the request records."""
        self.batcher.emit_synthetic_access(
            route, request_id=req.request_id, outcome=outcome,
            t_enq=req.t_in)

    @staticmethod
    def _query_tenant(query: str) -> Optional[str]:
        """The ``?tenant=`` selector on the scrape routes (healthz /
        stats) — the GET analog of the POST bodies' ``tenant`` field."""
        if not query:
            return None
        vals = urllib.parse.parse_qs(query).get("tenant")
        return vals[-1] if vals else None

    async def _route(self, req: _Request) -> tuple[int, dict]:
        target, _, query = req.target.partition("?")
        if target == "/healthz":
            if req.method != "GET":
                return 405, {"error": {"kind": "validation",
                                       "message": "/healthz wants GET"}}
            try:
                return self._healthz(self._query_tenant(query))
            except ServeError as e:  # unknown ?tenant= → 404, typed
                err = error_response(e)
                return _STATUS_BY_KIND[err["error"]["kind"]], err
        if target == "/metrics":
            # Prometheus text exposition of the whole registry
            # (telemetry/exposition.py; docs/observability.md "Live
            # metrics") — GET only, text/plain, scraper-ready
            if req.method != "GET":
                return 405, {"error": {"kind": "validation",
                                       "message": "/metrics wants GET"}}
            return 200, _TextPayload(render_prometheus())
        if target == "/v1/stats":
            if req.method not in ("GET", "POST"):
                return 405, {"error": {"kind": "validation",
                                       "message":
                                       "/v1/stats wants GET or POST"}}
            try:
                return 200, self._stats(self._query_tenant(query))
            except ServeError as e:  # unknown ?tenant= → 404, typed
                err = error_response(e)
                return _STATUS_BY_KIND[err["error"]["kind"]], err
        if target not in ("/v1/topk", "/v1/score", "/v1/upsert",
                          "/v1/delete", "/admin/rollover"):
            self._serve_access(req, "none", "validation")
            return 404, {"error": {"kind": "validation",
                                   "message": f"no route {target!r}"}}
        route = target.rsplit("/", 1)[-1]
        if req.method != "POST":
            self._serve_access(req, route, "validation")
            return 405, {"error": {"kind": "validation",
                                   "message": f"{target} wants POST"}}
        entered = [False]  # past this flag, the collator owns the access log
        try:
            try:
                body = json.loads(req.body.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                self._serve_access(req, route, "parse")
                return 400, {"error": {"kind": "parse",
                                       "message": str(e)}}
            if not isinstance(body, dict):
                raise ValueError(
                    f"request body must be a JSON object, got "
                    f"{type(body).__name__}")
            if target == "/admin/rollover":
                if self.rollover is None:
                    raise ValueError(
                        "no rollover coordinator armed on this server "
                        "(serve-http arms one when it can rebuild from "
                        "an artifact)")
                dest = body.get("target")
                if not isinstance(dest, str) or not dest:
                    raise ValueError(
                        "rollover needs \"target\": a non-empty "
                        "artifact path string")
                # prepare runs off-loop inside the coordinator; the
                # flip lands in one loop step — in-flight requests on
                # the old stack answer from the old engine
                resp = await self.rollover.rollover(dest)
            else:
                # multi-tenant routing (serve/registry.py): the body's
                # optional "tenant" field — a tenant name or an
                # artifact fingerprint — picks the serving stack;
                # absent routes to the default tenant (back-compat).
                # Unknown names answer the typed 404 (unknown_tenant).
                tenant_key = body.get("tenant")
                if self._registry is not None:
                    stack = self._registry.resolve(tenant_key)
                    # a paged-out tenant's engine rebuilds (coalesced,
                    # on the paging executor) before its dispatch
                    await self._registry.ensure_resident(stack)
                    async with self._registry.using(stack):
                        resp = await self._serve_op(
                            target, route, body, req,
                            stack.collator, entered)
                else:
                    if tenant_key is not None:
                        # single-tenant servers still honor fingerprint
                        # routing: the one engine's fingerprint resolves,
                        # anything else is the same typed 404 a registry
                        # would answer
                        from hyperspace_tpu.serve.errors import \
                            UnknownTenantError

                        if not isinstance(tenant_key, str) or not tenant_key:
                            raise ValueError(
                                "tenant must be a non-empty string, "
                                f"got {tenant_key!r}")
                        if tenant_key != self.batcher.engine.fingerprint:
                            raise UnknownTenantError(tenant_key)
                    resp = await self._serve_op(
                        target, route, body, req, self.collator, entered)
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            # the stdin loop's per-line error classes, mapped onto
            # status codes; an IO fault (incl. the serve.dispatch
            # ioerror chaos site) answers 500 and the server survives
            err = error_response(e)
            if not entered[0]:
                # validation failed before the collator saw the
                # request — it could not have emitted the record
                self._serve_access(req, route, err["error"]["kind"])
            return _STATUS_BY_KIND[err["error"]["kind"]], err
        return 200, resp

    async def _serve_op(self, target: str, route: str, body: dict,
                        req: _Request, coll: Collator,
                        entered: list) -> dict:
        """One serve op against the RESOLVED tenant's collator —
        the four /v1 dispatch bodies, factored so single- and
        multi-tenant routing share them verbatim."""
        if target == "/v1/topk":
            exclude_self = _json_bool(body, "exclude_self", True)
            deadline_ms = _req_deadline(body)
            entered[0] = True
            # the request envelope: the front door's root span scope,
            # keyed by the X-Request-Id — the collator's lifecycle
            # span becomes its child (spans off: a no-op)
            with spans.request(route, req.request_id):
                idx, dist = await coll.topk(
                    body.get("ids"), body.get("k", 10),
                    exclude_self=exclude_self,
                    deadline_ms=deadline_ms, t_enq=req.t_in,
                    request_id=req.request_id)
                return {"neighbors": idx.tolist(),
                        "dists": dist.tolist()}
        if target == "/v1/score":
            prob = _json_bool(body, "prob", False)
            fd_r = _req_number(body, "fd_r", 2.0)
            fd_t = _req_number(body, "fd_t", 1.0)
            deadline_ms = _req_deadline(body)
            entered[0] = True
            with spans.request(route, req.request_id):
                scores = await coll.score(
                    body.get("u"), body.get("v"), prob=prob,
                    fd_r=fd_r, fd_t=fd_t,
                    deadline_ms=deadline_ms, t_enq=req.t_in,
                    request_id=req.request_id)
                return {"scores": scores.tolist()}
        if target == "/v1/upsert":
            deadline_ms = _req_deadline(body)
            entered[0] = True
            with spans.request(route, req.request_id):
                return await coll.upsert(
                    body.get("ids"), body.get("rows"),
                    deadline_ms=deadline_ms, t_enq=req.t_in,
                    request_id=req.request_id)
        # /v1/delete (the route set is closed upstream)
        deadline_ms = _req_deadline(body)
        entered[0] = True
        with spans.request(route, req.request_id):
            return await coll.delete(
                body.get("ids"),
                deadline_ms=deadline_ms, t_enq=req.t_in,
                request_id=req.request_id)

    def _healthz(self, tenant_key: Optional[str] = None
                 ) -> tuple[int, dict]:
        """The load-balancer body (docs/serving.md "HTTP front door"):
        bare ok plus the fields a blue-green flip checks before routing
        traffic — uptime, package version, which artifact (fingerprint)
        and which program (scan signature, precision lane) this server
        answers with, and whether it is currently degraded.  With a
        registry armed the body carries a per-tenant summary list;
        ``?tenant=`` narrows to one tenant (404 on unknown names —
        the identity fields then come from the stack's captured build
        identity, so a PAGED-OUT tenant still answers without a
        rebuild)."""
        ok = not self._draining.is_set()
        if self._registry is not None:
            out = {"ok": ok, "draining": not ok,
                   "uptime_s": round(time.monotonic() - self.t_start, 3),
                   "version": hyperspace_tpu.__version__}
            if tenant_key is not None:
                # raises UnknownTenantError → the caller's 404 path
                out.update(self._registry.resolve(tenant_key).summary())
            else:
                d = self._registry.default
                out["fingerprint"] = d.fingerprint
                out["tenant"] = d.name
                out["tenants"] = [s.summary()
                                  for s in self._registry.tenants()]
            return (200 if ok else 503), out
        if tenant_key is not None and (
                tenant_key != self.batcher.engine.fingerprint):
            from hyperspace_tpu.serve.errors import UnknownTenantError

            raise UnknownTenantError(tenant_key)
        eng = self.batcher.engine
        return (200 if ok else 503), {
            "ok": ok,
            "draining": not ok,
            "uptime_s": round(time.monotonic() - self.t_start, 3),
            "version": hyperspace_tpu.__version__,
            "fingerprint": eng.fingerprint,
            "scan_signature": list(eng.scan_signature),
            "precision": eng.precision,
            "degrade_level": self.batcher.degrade_level,
            # live engines only (serve/delta.py): the segment
            # generation a zero-staleness client can pin; None = frozen
            "generation": getattr(eng, "generation", None),
        }

    def _stats(self, tenant_key: Optional[str] = None) -> dict:
        if self._registry is not None:
            tenants = self._registry.stats()
            if tenant_key is not None:
                # raises UnknownTenantError → the caller's 404 path
                out = dict(tenants[self._registry.resolve(tenant_key)
                                   .name])
            else:
                out = dict(tenants[self._registry.default.name])
                out["tenants"] = tenants
        else:
            out = dict(self.batcher.stats())
        out["server"] = {"served": self.served,
                         "inflight": self.inflight,
                         "draining": self.draining,
                         "max_wait_us": round(
                             self.collator.max_wait_s * 1e6, 1)}
        # compile-count beside the serve stats: the smoke/bench contract
        # is recompiles FLAT across same-bucket requests after warmup
        out["recompiles"] = telem.default_registry().get("jax/recompiles")
        out["collator_flushes"] = telem.default_registry().get(
            "serve/collator_flushes")
        return out

    # --- response write -------------------------------------------------------

    async def _write_response(self, writer, status: int, payload,
                              *, close: bool,
                              request_id: Optional[str] = None) -> None:
        if isinstance(payload, _TextPayload):
            body = str(payload).encode("utf-8")
            ctype = payload.content_type
        else:
            body = json.dumps(payload,
                              default=_json_default).encode("utf-8")
            ctype = "application/json"
        rid = (f"X-Request-Id: {request_id}\r\n"
               if request_id is not None else "")
        head = (f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n{rid}"
                f"Connection: {'close' if close else 'keep-alive'}\r\n"
                "\r\n").encode("latin-1")
        writer.write(head + body)
        await writer.drain()
        self.served += 1
        telem.inc("serve/http_requests")


def latency_summary_line(baseline: Optional[dict] = None) -> str:
    """One-line ``serve/e2e_ms`` summary — the stdin loop's exit line,
    shared by the serve-http CLI (count + p50/p95/p99, optionally as a
    delta over a session-start registry mark)."""
    snap = telem.default_registry().snapshot(baseline=baseline)
    lat = snap.get("hist/serve/e2e_ms")
    if not lat or not lat.get("count"):
        return "[serve] latency e2e_ms: no requests"
    return ("[serve] latency e2e_ms count=%d p50=%.3f p95=%.3f p99=%.3f"
            % (lat["count"], lat["p50"], lat["p95"], lat["p99"]))


async def run_front_door(batcher: Optional[RequestBatcher] = None, *,
                         host: str, port: int,
                         max_wait_us: float = DEFAULT_MAX_WAIT_US,
                         ready=None, prewarm_ks=None,
                         rollover_builder=None, registry=None) -> dict:
    """Start, announce, serve until drained (SIGTERM), summarize.

    ``ready(host, port)`` is called once the listener is bound (the CLI
    prints the parseable "listening" line there; tests grab the
    ephemeral port).  ``prewarm_ks`` (a list of k values) compiles the
    whole bucket ladder **before the listeners open** —
    :meth:`RequestBatcher.prewarm`, docs/serving.md "Warm starts" — so
    the first request a client can possibly land on any bucket is warm
    (and ``/healthz`` cannot answer ok while the ladder is still cold).
    ``rollover_builder(target)`` (a blocking callable returning a
    standby :class:`RequestBatcher`) arms ``POST /admin/rollover``
    (serve/rollover.py) — the standby is prewarmed over the same
    ``prewarm_ks`` before the gate-checked flip.
    ``registry=`` (a :class:`~hyperspace_tpu.serve.registry.
    EngineRegistry`) serves EVERY registered tenant behind this one
    door instead of a single batcher — prewarm then warms each
    resident tenant's ladder.  Returns the closing stats dict."""
    door = HttpFrontDoor(batcher, host=host, port=port,
                         max_wait_us=max_wait_us, registry=registry)
    if rollover_builder is not None:
        from hyperspace_tpu.serve.rollover import RolloverCoordinator

        door.rollover = RolloverCoordinator(
            door, rollover_builder, prewarm_ks=prewarm_ks or None)
    session_mark = telem.default_registry().mark()
    if prewarm_ks:
        # deliberately blocking: nothing is listening yet, and a warm
        # ladder is the precondition for opening the door at all
        if registry is not None:
            infos = registry.prewarm(prewarm_ks)
            progs = sum(i["programs"] for i in infos.values())
            secs = sum(i["seconds"] for i in infos.values())
            info = {"programs": progs, "seconds": secs}
        else:
            info = door.batcher.prewarm(prewarm_ks)
        try:
            print(f"[serve-http] prewarmed {info['programs']} "
                  f"program(s) in {info['seconds']:.2f}s",
                  file=sys.stderr, flush=True)
        except (OSError, ValueError):
            pass  # hyperlint: disable=swallow-base-exception — closed stderr: announcement loss only
    await door.start()
    if ready is not None:
        ready(door.host, door.port)
    await door.serve_until_drained()
    try:
        print(f"[serve-http] drained: stopped accepting, "
              f"{door.served} response(s) sent", file=sys.stderr,
              flush=True)
        if door.aborted_connections:
            # an honest drain never claims requests it abandoned
            print(f"[serve-http] WARNING: {door.aborted_connections} "
                  "connection(s) still in flight at the drain timeout "
                  "were abandoned", file=sys.stderr, flush=True)
        print(latency_summary_line(session_mark), file=sys.stderr,
              flush=True)
    except (OSError, ValueError):
        pass  # hyperlint: disable=swallow-base-exception — closed stderr: diagnostics loss, never a drain failure
    return {"served": door.served, "drained": True,
            "aborted_connections": door.aborted_connections}
