"""Frozen, params-only serving artifacts (docs/serving.md).

A training checkpoint is the wrong thing to serve from: it carries
optimizer moments (2-3x the bytes of the params at embedding scale),
its layout is the train-state pytree (restore needs the model/optimizer
objects that built it), and orbax's directory format is a tree of
tensorstore shards.  A *serving artifact* is the frozen inference view:

- ``table.npy``   — the [N, D] embedding table, bit-exact (``np.save``);
- ``artifact.json`` — manifold spec (kind + curvature(s), per-factor for
  products), the model config as exported, table shape/dtype, a content
  fingerprint, and the source checkpoint step;
- ``index.npz``   — OPTIONAL: the IVF index arrays (centroids, dense
  cell layout, counts — ``serve/index.py``), with its own content hash
  in the meta block and folded into the artifact fingerprint;
- ``quant.npz``   — OPTIONAL: a packed scan lane (:class:`QuantPayload`
  — int4 nibbles + scales, or PQ codes + trained codebooks), content-
  hashed and folded into the artifact fingerprint the same way;
- ``COMMITTED``   — the commit marker, WRITTEN LAST.

Writes are atomic the same way checkpoints are: everything lands in a
staging directory (``.<name>.tmp.<pid>`` under the same parent), the
marker goes in last, and one ``os.rename`` commits.  A crash mid-export
leaves either a marker-less staging dir (ignored by :func:`load_artifact`
/ :func:`is_committed`) or nothing at the final name — never a
half-written artifact that loads.

The **fingerprint** (sha256 over the table bytes + shape + dtype + the
canonical manifold-spec JSON) names the content, not the path: it keys
the request batcher's result cache (``serve/batcher.py``), and the
round-trip lint (``scripts/check_serve_artifact.py``) uses it to assert
export → load is the identity.

Manifold specs are canonical nested tuples — hashable, so the query
engine can hang them on ``jax.jit`` static arguments:

    ("poincare", 1.0)
    ("lorentz", 0.8)
    ("product", (("poincare", 5, 1.3), ("sphere", 5, 0.9),
                 ("euclidean", 2, 0.0)))
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
from typing import Any, Optional

import numpy as np

ARTIFACT_VERSION = 1
COMMIT_MARKER = "COMMITTED"
META_FILE = "artifact.json"
TABLE_FILE = "table.npy"
INDEX_FILE = "index.npz"  # optional IVF index (serve/index.py)
QUANT_FILE = "quant.npz"  # optional packed scan lane (serve/quant.py)


# --- manifold specs -----------------------------------------------------------


def spec_from_manifold(m) -> tuple:
    """Canonical spec tuple for a manifold instance (curvatures are read
    as concrete floats — specs describe FROZEN geometry, so a traced
    curvature must be materialized by the exporter first)."""
    from hyperspace_tpu.manifolds import (Euclidean, Lorentz, PoincareBall,
                                          Product, Sphere)

    if isinstance(m, Product):
        def fspec(f, d):
            kind, c = spec_from_manifold(f)
            return (kind, int(d), c)

        return ("product", tuple(
            fspec(f, d) for f, d in zip(m.factors, m.dims)))
    if isinstance(m, PoincareBall):
        return ("poincare", float(m.c))
    if isinstance(m, Lorentz):
        return ("lorentz", float(m.c))
    if isinstance(m, Sphere):
        return ("sphere", float(m.c))
    if isinstance(m, Euclidean):
        return ("euclidean", 0.0)
    raise ValueError(f"no serving spec for manifold {type(m).__name__}")


def manifold_from_spec(spec: tuple):
    """Build the manifold a spec names (inverse of
    :func:`spec_from_manifold`; jit-safe — curvatures are floats)."""
    from hyperspace_tpu.manifolds import (Euclidean, Lorentz, PoincareBall,
                                          Product, Sphere)

    kinds = {"poincare": PoincareBall, "lorentz": Lorentz, "sphere": Sphere}
    kind = spec[0]
    if kind == "product":
        factors, dims = [], []
        for fkind, dim, c in spec[1]:
            factors.append(Euclidean() if fkind == "euclidean"
                           else kinds[fkind](float(c)))
            dims.append(int(dim))
        return Product(factors, dims)
    if kind == "euclidean":
        return Euclidean()
    if kind in kinds:
        return kinds[kind](float(spec[1]))
    raise ValueError(f"unknown manifold spec kind {kind!r}")


def spec_to_json(spec: tuple) -> dict:
    kind = spec[0]
    if kind == "product":
        return {"kind": "product", "factors": [
            {"kind": fk, "dim": int(d), "c": float(c)}
            for fk, d, c in spec[1]]}
    return {"kind": kind, "c": float(spec[1])}


def spec_from_json(doc: dict) -> tuple:
    kind = doc["kind"]
    if kind == "product":
        return ("product", tuple(
            (f["kind"], int(f["dim"]), float(f.get("c", 0.0)))
            for f in doc["factors"]))
    return (kind, float(doc.get("c", 0.0)))


def spec_dim(spec: tuple) -> int:
    """Ambient (storage) width the spec expects of a table row."""
    if spec[0] == "product":
        return sum(int(d) for _k, d, _c in spec[1])
    return -1  # unconstrained for single-manifold specs


# --- fingerprint --------------------------------------------------------------


def fingerprint_of(table: np.ndarray, spec: tuple,
                   index_fingerprint: Optional[str] = None,
                   quant_fingerprint: Optional[str] = None) -> str:
    """Content identity: sha256 over the table bytes, its shape/dtype,
    and the canonical spec JSON.  Same table + geometry → same
    fingerprint, wherever the artifact lives on disk.  An attached IVF
    index or packed quant lane folds its own content hash in
    (``index_fingerprint`` / ``quant_fingerprint``), so an artifact
    carrying either is a DIFFERENT artifact than the bare table —
    without them the hash is byte-identical to the pre-index format
    (existing fingerprints stay valid)."""
    table = np.ascontiguousarray(table)
    doc = {"spec": spec_to_json(spec),
           "shape": list(table.shape),
           "dtype": str(table.dtype)}
    if index_fingerprint is not None:
        doc["index"] = index_fingerprint
    if quant_fingerprint is not None:
        doc["quant"] = quant_fingerprint
    h = hashlib.sha256()
    h.update(json.dumps(doc, sort_keys=True).encode())
    h.update(table.tobytes())
    return h.hexdigest()


# --- quantized scan payloads --------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QuantPayload:
    """A packed scan-lane copy shipped INSIDE an artifact.

    The serve-time quantized copy (``serve/quant.py``) is normally
    derived from the f32 master on engine construction; shipping it in
    the artifact makes the derivation part of the frozen content — PQ
    codebooks in particular are TRAINED (subspace k-means), so two
    engines built from the same master but different codebooks rank
    candidates differently, and the payload pins which codebooks serve.

    ``lane`` names the precision ("int4" | "pq"), ``arrays`` holds the
    packed content (int4: ``packed`` uint8 [N, ceil(D/2)] + ``scale``
    f16 [N, 1]; pq: ``codes`` uint8 [N, m] + ``codebooks`` f32
    [m, 256, ds]), ``params`` the scalar geometry the engine needs to
    decode (int4: ``dim``; pq: ``m``/``lift_dim``/``iters``/``seed``),
    and ``fingerprint`` the content hash ``load_artifact`` re-verifies.
    """

    lane: str
    arrays: dict
    params: dict
    fingerprint: str

    @property
    def num_nodes(self) -> int:
        key = "packed" if "packed" in self.arrays else "codes"
        return int(self.arrays[key].shape[0])


def quant_fingerprint_of(lane: str, arrays: dict, params: dict) -> str:
    """Content hash of a packed lane: sha256 over the lane tag, the
    decode params, every array's shape/dtype, and the raw bytes (arrays
    walked in sorted-key order, so dict insertion order never leaks into
    the identity)."""
    doc = {"lane": str(lane),
           "params": {k: params[k] for k in sorted(params)},
           "arrays": {k: [list(arrays[k].shape), str(arrays[k].dtype)]
                      for k in sorted(arrays)}}
    h = hashlib.sha256()
    h.update(json.dumps(doc, sort_keys=True).encode())
    for k in sorted(arrays):
        h.update(np.ascontiguousarray(arrays[k]).tobytes())
    return h.hexdigest()


def build_quant_payload(table, spec: tuple, lane: str, *,
                        pq_m: int = 0, pq_iters: int = 6,
                        pq_seed: int = 0) -> QuantPayload:
    """Pack ``table`` for ``lane`` exactly as a live engine would.

    int4 packs per-row nibbles + f16 scales; pq trains lifted-subspace
    codebooks (``serve/quant.py:build_pq`` — deterministic in
    ``pq_seed``) and encodes every row.  The returned payload plugs into
    :func:`export_artifact`'s ``quant=`` and the engine's ``quant=``.
    """
    table = np.ascontiguousarray(np.asarray(table, np.float32))
    if table.ndim != 2:
        raise ValueError(f"table must be [N, D]; got {table.shape}")
    if lane == "int4":
        from hyperspace_tpu.serve.quant import pack_int4_rows

        packed, scale = pack_int4_rows(table)
        arrays = {"packed": packed, "scale": scale}
        params = {"dim": int(table.shape[1])}
    elif lane == "pq":
        from hyperspace_tpu.serve.quant import build_pq

        codes, cb = build_pq(table, spec, m=pq_m, iters=pq_iters,
                             seed=pq_seed)
        arrays = {"codes": codes, "codebooks": cb.codebooks}
        params = {"m": int(cb.m), "lift_dim": int(cb.lift_dim),
                  "iters": int(cb.iters), "seed": int(cb.seed)}
    else:
        raise ValueError(
            f"quant payloads cover lanes ('int4', 'pq'); got {lane!r}")
    return QuantPayload(lane=lane, arrays=arrays, params=params,
                        fingerprint=quant_fingerprint_of(
                            lane, arrays, params))


# --- the artifact -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServingArtifact:
    """A loaded (or about-to-be-written) serving artifact."""

    table: np.ndarray           # [N, D] host array, bit-exact
    manifold_spec: tuple        # canonical spec tuple (hashable)
    model_config: dict          # exported model config (JSON-safe)
    fingerprint: str
    step: Optional[int] = None  # source checkpoint step, if any
    index: Optional[object] = None  # ServingIndex (serve/index.py) or None
    quant: Optional[QuantPayload] = None  # packed scan lane or None

    @property
    def num_nodes(self) -> int:
        return int(self.table.shape[0])

    @property
    def dim(self) -> int:
        return int(self.table.shape[1])

    def manifold(self):
        return manifold_from_spec(self.manifold_spec)


def _make_artifact(table, spec, model_config, step,
                   index=None, quant=None) -> ServingArtifact:
    table = np.ascontiguousarray(np.asarray(table))
    if table.ndim != 2:
        raise ValueError(f"serving table must be [N, D]; got {table.shape}")
    want = spec_dim(spec)
    if want >= 0 and table.shape[1] != want:
        raise ValueError(
            f"table width {table.shape[1]} != product spec width {want}")
    if index is not None:
        if int(index.num_nodes) != table.shape[0]:
            raise ValueError(
                f"index covers {index.num_nodes} rows; table has "
                f"{table.shape[0]} — rebuild the index for THIS table")
        if int(index.centroids.shape[1]) != table.shape[1]:
            raise ValueError(
                f"index centroid width {index.centroids.shape[1]} != "
                f"table width {table.shape[1]}")
    if quant is not None and int(quant.num_nodes) != table.shape[0]:
        raise ValueError(
            f"quant payload covers {quant.num_nodes} rows; table has "
            f"{table.shape[0]} — re-pack for THIS table")
    return ServingArtifact(
        table=table, manifold_spec=spec,
        model_config=dict(model_config or {}),
        fingerprint=fingerprint_of(
            table, spec, None if index is None else index.fingerprint,
            None if quant is None else quant.fingerprint),
        step=None if step is None else int(step),
        index=index, quant=quant)


def _process_topology() -> tuple[int, int]:
    """(process_index, process_count) without importing jax: the serve
    plane must stay importable (and fast) in jax-free consumers, so the
    topology is read only when jax is ALREADY loaded and initialized."""
    import sys

    if "jax" not in sys.modules:
        return 0, 1
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:  # backend not initialized yet: single process
        return 0, 1


def export_artifact(directory: str, table, manifold_spec: tuple, *,
                    model_config: Optional[dict] = None,
                    step: Optional[int] = None,
                    overwrite: bool = False,
                    index=None, quant=None) -> ServingArtifact:
    """Write a serving artifact atomically; returns the artifact written.

    Staging dir + marker-last + one ``os.rename`` (module docstring).
    An existing COMMITTED artifact at ``directory`` is an error unless
    ``overwrite=True`` (then it is replaced; the replace itself is
    rename-then-delete, so a reader holding the old dir open keeps a
    consistent view).

    Multi-process safe: in a ``jax.distributed`` run, process 0 ALONE
    writes (a pod run yields ONE artifact — N processes racing the
    staging rename would corrupt nothing but would leave N-1 stranded
    ``.old`` trees); the other processes wait at a barrier for the
    commit and return the committed artifact.  Every process must call
    this (it is a collective).
    """
    pi, pc = _process_topology()
    if pc > 1 and pi != 0:
        from hyperspace_tpu.parallel import multihost as mh

        mh.sync("artifact_export")  # meets process 0's post-commit sync
        return load_artifact(directory)
    art = _make_artifact(table, manifold_spec, model_config, step, index,
                         quant)
    directory = os.path.abspath(directory)
    parent = os.path.dirname(directory)
    os.makedirs(parent, exist_ok=True)
    if os.path.exists(directory):
        if not overwrite:
            raise FileExistsError(
                f"serving artifact already exists at {directory} "
                "(pass overwrite=True to replace)")
    staging = os.path.join(
        parent, f".{os.path.basename(directory)}.tmp.{os.getpid()}")
    if os.path.exists(staging):
        shutil.rmtree(staging)
    os.makedirs(staging)
    try:
        np.save(os.path.join(staging, TABLE_FILE), art.table)
        meta = {
            "version": ARTIFACT_VERSION,
            "manifold": spec_to_json(art.manifold_spec),
            "model_config": art.model_config,
            "table": {"shape": list(art.table.shape),
                      "dtype": str(art.table.dtype)},
            "fingerprint": art.fingerprint,
            "step": art.step,
        }
        if art.index is not None:
            np.savez(os.path.join(staging, INDEX_FILE),
                     centroids=art.index.centroids, cells=art.index.cells,
                     counts=art.index.counts)
            meta["index"] = {
                "ncells": art.index.ncells, "max_cell": art.index.max_cell,
                "num_nodes": art.index.num_nodes, "iters": art.index.iters,
                "seed": art.index.seed,
                "fingerprint": art.index.fingerprint,
            }
        if art.quant is not None:
            np.savez(os.path.join(staging, QUANT_FILE), **art.quant.arrays)
            meta["quant"] = {
                "lane": art.quant.lane,
                "params": dict(art.quant.params),
                "arrays": sorted(art.quant.arrays),
                "fingerprint": art.quant.fingerprint,
            }
        with open(os.path.join(staging, META_FILE), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        # marker LAST: everything before it is on disk when it appears
        with open(os.path.join(staging, COMMIT_MARKER), "w") as f:
            f.write(art.fingerprint + "\n")
        if os.path.exists(directory):  # overwrite=True path
            old = directory + f".old.{os.getpid()}"
            if os.path.exists(old):  # pid reuse after a prior crash
                shutil.rmtree(old)
            os.rename(directory, old)
            try:
                os.rename(staging, directory)
            except BaseException:
                # an interrupt between the renames must not strand the
                # target empty: put the prior committed artifact back
                os.rename(old, directory)
                raise
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(staging, directory)
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    if pc > 1:
        from hyperspace_tpu.parallel import multihost as mh

        mh.sync("artifact_export")  # release the waiting processes
    return art


def is_committed(directory: str) -> bool:
    """Whether ``directory`` holds a committed serving artifact."""
    return (os.path.isfile(os.path.join(directory, COMMIT_MARKER))
            and os.path.isfile(os.path.join(directory, META_FILE))
            and os.path.isfile(os.path.join(directory, TABLE_FILE)))


def load_artifact(directory: str) -> ServingArtifact:
    """Load a committed artifact; verifies the content fingerprint.

    Raises ``FileNotFoundError`` for a missing/uncommitted directory and
    ``ValueError`` for a fingerprint mismatch (bit rot, or files swapped
    under the marker) — a serving process must never come up on a table
    that is not the one the exporter hashed.
    """
    directory = os.path.abspath(directory)
    if not is_committed(directory):
        raise FileNotFoundError(
            f"no committed serving artifact at {directory}")
    with open(os.path.join(directory, META_FILE)) as f:
        meta = json.load(f)
    if int(meta.get("version", -1)) != ARTIFACT_VERSION:
        raise ValueError(
            f"artifact version {meta.get('version')!r} != "
            f"{ARTIFACT_VERSION} at {directory}")
    table = np.load(os.path.join(directory, TABLE_FILE))
    spec = spec_from_json(meta["manifold"])
    index = None
    if meta.get("index") is not None:
        # ServingIndex lives in serve/index.py, which imports this
        # module — resolve it lazily so artifact.py stays import-cycle
        # free at module load
        from hyperspace_tpu.serve.index import (ServingIndex,
                                                index_fingerprint_of)

        imeta = meta["index"]
        ipath = os.path.join(directory, INDEX_FILE)
        if not os.path.isfile(ipath):
            raise ValueError(
                f"artifact meta names an index but {INDEX_FILE} is "
                f"missing at {directory}")
        with np.load(ipath) as z:
            centroids = np.ascontiguousarray(z["centroids"])
            cells = np.ascontiguousarray(z["cells"])
            counts = np.ascontiguousarray(z["counts"])
        try:
            imeta = {k: imeta[k] for k in
                     ("num_nodes", "iters", "seed", "fingerprint")}
        except KeyError as e:
            # keep the module's corrupt-artifact convention: every load
            # failure is a ValueError the CLI turns into a clean exit
            raise ValueError(
                f"artifact index meta at {directory} is missing {e}") from None
        ifp = index_fingerprint_of(
            centroids, cells, counts, num_nodes=int(imeta["num_nodes"]),
            iters=int(imeta["iters"]), seed=int(imeta["seed"]))
        if ifp != imeta["fingerprint"]:
            raise ValueError(
                f"index fingerprint mismatch at {directory}: meta says "
                f"{imeta['fingerprint'][:12]}…, content is {ifp[:12]}…")
        index = ServingIndex(
            centroids=centroids, cells=cells, counts=counts,
            num_nodes=int(imeta["num_nodes"]), iters=int(imeta["iters"]),
            seed=int(imeta["seed"]), fingerprint=ifp)
    quant = None
    if meta.get("quant") is not None:
        qmeta = meta["quant"]
        qpath = os.path.join(directory, QUANT_FILE)
        if not os.path.isfile(qpath):
            raise ValueError(
                f"artifact meta names a quant lane but {QUANT_FILE} is "
                f"missing at {directory}")
        try:
            lane, params = qmeta["lane"], dict(qmeta["params"])
            names = list(qmeta["arrays"])
        except KeyError as e:
            raise ValueError(
                f"artifact quant meta at {directory} is missing {e}") \
                from None
        with np.load(qpath) as z:
            missing = sorted(set(names) - set(z.files))
            if missing:
                raise ValueError(
                    f"quant payload at {directory} is missing arrays "
                    f"{missing}")
            arrays = {k: np.ascontiguousarray(z[k]) for k in names}
        # recompute, never trust: a tampered codebook/packed table would
        # otherwise serve silently-wrong candidate rankings
        qfp = quant_fingerprint_of(lane, arrays, params)
        if qfp != qmeta["fingerprint"]:
            raise ValueError(
                f"quant fingerprint mismatch at {directory}: meta says "
                f"{qmeta['fingerprint'][:12]}…, content is {qfp[:12]}…")
        quant = QuantPayload(lane=lane, arrays=arrays, params=params,
                             fingerprint=qfp)
    fp = fingerprint_of(table, spec,
                        None if index is None else index.fingerprint,
                        None if quant is None else quant.fingerprint)
    if fp != meta["fingerprint"]:
        raise ValueError(
            f"artifact fingerprint mismatch at {directory}: "
            f"meta says {meta['fingerprint'][:12]}…, content is {fp[:12]}…")
    return ServingArtifact(
        table=table, manifold_spec=spec,
        model_config=meta.get("model_config") or {},
        fingerprint=fp, step=meta.get("step"), index=index, quant=quant)


# --- checkpoint → artifact ----------------------------------------------------


def export_from_checkpoint(ckpt_dir: str, out_dir: str, *,
                           workload: str,
                           model_config: Optional[dict] = None,
                           step: Optional[int] = None,
                           overwrite: bool = False,
                           index_ncells: Optional[int] = None,
                           quant_lane: Optional[str] = None
                           ) -> ServingArtifact:
    """Export the newest committed checkpoint step as a serving artifact.

    Restores the raw state pytree via
    :func:`hyperspace_tpu.train.checkpoint.restore_params_only` (no
    optimizer/model objects) and extracts the embedding table + frozen
    geometry per workload:

    - ``poincare``: ``tree["table"]`` on ``PoincareBall(c)`` —
      ``model_config["c"]`` is REQUIRED (the trained curvature is not
      in the checkpoint; there is deliberately no silent default);
    - ``lorentz``: ``tree["table"]`` on ``Lorentz(c)`` (same required
      config key) — for Lorentz-stored embedding tables;
    - ``product``: ``tree["params"]["table"]`` +
      ``tree["params"]["c_raw"]``; factor layout from
      ``model_config["factors"]`` ([(kind, dim), ...] —
      ``ProductEmbedConfig.factors``; defaults to that config's default)
      with the LEARNED curvatures ``softplus(c_raw)`` frozen into the
      spec.

    (HGCN/HyboNet/HVAE checkpoints hold deep parameter trees, not one
    retrieval table — out of scope for the embedding query engine.)

    ``index_ncells`` builds an IVF index over the exported table
    (``serve/index.py``; hyperbolic k-means with that many cells —
    ``<= 0`` picks ``auto_ncells`` ≈ √N) and ships it inside the
    artifact — CLI ``export index=1 [ncells=K]``.

    ``quant_lane`` ("int4" | "pq") packs the exported table for that
    scan lane (:func:`build_quant_payload`) and ships the payload — CLI
    ``export quant=int4|pq``; a pq export freezes the TRAINED codebooks
    into the artifact, so every serving replica ranks through the same
    centers.
    """
    from hyperspace_tpu.train.checkpoint import restore_params_only

    tree, ck_step = restore_params_only(ckpt_dir, step=step)
    cfg = dict(model_config or {})
    if workload in ("poincare", "lorentz"):
        if "c" not in cfg:
            # the trained curvature lives only in the (un-checkpointed)
            # model config — a silent 1.0 default would freeze the WRONG
            # metric into a committed, fingerprint-valid artifact
            raise ValueError(
                f"{workload} export requires model_config['c'] (the "
                "curvature the run trained with; it is not recoverable "
                "from the checkpoint state)")
        spec = (workload, float(cfg["c"]))
        table = np.asarray(tree["table"])
    elif workload == "product":
        factors = cfg.get("factors")
        if factors is None:
            from hyperspace_tpu.models.product_embed import ProductEmbedConfig

            factors = list(ProductEmbedConfig.factors)
        import jax.numpy as jnp
        from jax.nn import softplus

        # the SAME softplus the live model applies (product_embed.
        # build_manifold), in c_raw's own stored dtype — not upcast, so
        # the frozen curvature is bit-wise the one the run trained under
        curv = np.asarray(softplus(jnp.asarray(
            np.asarray(tree["params"]["c_raw"]))))
        factors = [tuple(f) for f in factors]
        want = sum(1 for kind, _d in factors if kind != "euclidean")
        if want != curv.shape[0]:  # check BEFORE indexing curv
            raise ValueError(
                f"factor layout {factors} expects {want} learned "
                f"curvatures; checkpoint has {curv.shape[0]}")
        fspec, i = [], 0
        for kind, dim in factors:
            if kind == "euclidean":
                fspec.append(("euclidean", int(dim), 0.0))
            else:
                fspec.append((kind, int(dim), float(curv[i])))
                i += 1
        spec = ("product", tuple(fspec))
        table = np.asarray(tree["params"]["table"])
        cfg["factors"] = [list(f) for f in factors]
    else:
        raise ValueError(
            f"export_from_checkpoint: unknown workload {workload!r} "
            "(want poincare|lorentz|product)")
    index = None
    if index_ncells is not None:
        from hyperspace_tpu.serve.index import auto_ncells, build_index

        ncells = int(index_ncells)
        if ncells <= 0:
            ncells = auto_ncells(int(table.shape[0]))
        index = build_index(table, spec, ncells)
    quant = (build_quant_payload(table, spec, quant_lane)
             if quant_lane else None)
    return export_artifact(out_dir, table, spec, model_config=cfg,
                           step=ck_step, overwrite=overwrite, index=index,
                           quant=quant)
