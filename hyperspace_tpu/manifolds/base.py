"""The Manifold interface every geometry implements.

A manifold object is a *pytree* whose only leaves are its (possibly traced)
curvature parameters, so a manifold can be passed through ``jax.jit`` /
``jax.grad`` boundaries and its curvature can be a learned value
(BASELINE.json configs[4]: product manifolds with learned curvature).

All point/tangent arrays are batched over leading axes; the manifold
dimension is always the last axis.
"""

from __future__ import annotations

import abc

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath


def reduce_health_stats(groups) -> dict:
    """Combine same-named health stats from several sources (product
    factors, tagged param leaves) by the suffix convention the telemetry
    monitor thresholds on: ``*_min`` → min, ``*_mean`` → mean (of
    means — unweighted, a deliberate approximation), anything else →
    max.  The ONE implementation shared by ``Product.health_stats`` and
    ``telemetry.health.health_stats`` so the reduction rules can never
    drift apart."""
    agg: dict = {}
    for stats in groups:
        for k, v in stats.items():
            agg.setdefault(k, []).append(v)
    out = {}
    for k, vs in agg.items():
        if len(vs) == 1:
            out[k] = vs[0]
        elif k.endswith("_min"):
            out[k] = jnp.min(jnp.stack(vs))
        elif k.endswith("_mean"):
            out[k] = jnp.mean(jnp.stack(vs))
        else:
            out[k] = jnp.max(jnp.stack(vs))
    return out


class Manifold(abc.ABC):
    """Abstract Riemannian manifold.

    The method set mirrors the primitive inventory of the reference's CUDA
    backend (SURVEY.md §0: expmap/logmap, parallel transport, distance,
    projections, plus Möbius ops on gyrovector manifolds).
    """

    name: str = "manifold"

    # --- core geometry --------------------------------------------------------

    @abc.abstractmethod
    def proj(self, x: jax.Array) -> jax.Array:
        """Project an ambient point onto the manifold (numerical guard)."""

    @abc.abstractmethod
    def proju(self, x: jax.Array, u: jax.Array) -> jax.Array:
        """Project an ambient vector onto the tangent space at ``x``."""

    @abc.abstractmethod
    def expmap(self, x: jax.Array, v: jax.Array) -> jax.Array:
        """Exponential map of tangent ``v`` at point ``x``."""

    @abc.abstractmethod
    def logmap(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """Logarithm map of ``y`` at base point ``x``."""

    @abc.abstractmethod
    def sqdist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """Squared geodesic distance, shape = broadcast batch (no last axis)."""

    @abc.abstractmethod
    def inner(self, x: jax.Array, u: jax.Array, v: jax.Array, keepdims: bool = False) -> jax.Array:
        """Riemannian inner product of tangents ``u``, ``v`` at ``x``."""

    @abc.abstractmethod
    def ptransp(self, x: jax.Array, y: jax.Array, v: jax.Array) -> jax.Array:
        """Parallel transport of tangent ``v`` from ``x`` to ``y``."""

    @abc.abstractmethod
    def egrad2rgrad(self, x: jax.Array, g: jax.Array) -> jax.Array:
        """Convert a Euclidean gradient into a Riemannian gradient at ``x``."""

    @abc.abstractmethod
    def origin(self, shape, dtype=jnp.float32) -> jax.Array:
        """The canonical base point ('origin') broadcast to ``shape``."""

    # --- defaults -------------------------------------------------------------

    def dist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        return smath.safe_sqrt(self.sqdist(x, y))

    def norm_t(self, x: jax.Array, u: jax.Array, keepdims: bool = False) -> jax.Array:
        return smath.safe_sqrt(self.inner(x, u, u, keepdims=keepdims))

    def expmap0(self, v: jax.Array) -> jax.Array:
        """Exponential map at the origin."""
        return self.expmap(self.origin(v.shape, v.dtype), v)

    def logmap0(self, y: jax.Array) -> jax.Array:
        """Logarithm map at the origin."""
        return self.logmap(self.origin(y.shape, y.dtype), y)

    def ptransp0(self, y: jax.Array, v: jax.Array) -> jax.Array:
        """Parallel transport from the origin to ``y``."""
        return self.ptransp(self.origin(y.shape, y.dtype), y, v)

    def retr(self, x: jax.Array, v: jax.Array) -> jax.Array:
        """First-order retraction (cheap expmap substitute): proj(x + v)."""
        return self.proj(x + v)

    def zero_tangent(self, x: jax.Array) -> jax.Array:
        return jnp.zeros_like(x)

    def random_normal(self, key: jax.Array, shape, dtype=jnp.float32, std: float = 1.0) -> jax.Array:
        """A wrapped-normal sample: N(0, std) in the origin tangent → expmap0."""
        v = std * jax.random.normal(key, shape, dtype)
        v = self.proju(self.origin(v.shape, dtype), v)
        return self.proj(self.expmap0(v))

    def check_point(self, x: jax.Array) -> jax.Array:
        """Residual of the manifold constraint (0 for on-manifold points)."""
        return jnp.zeros(x.shape[:-1], x.dtype)

    def health_stats(self, x: jax.Array) -> dict:
        """Numerical-health scalars for a batch of points (jit-safe).

        The telemetry layer samples these on device
        (``telemetry/health.py``); geometries with a specific blow-up
        mode override with their leading indicator (ball: distance to
        boundary; hyperboloid: constraint residual).  The generic
        default reports the ``check_point`` residual.
        """
        v = self.check_point(x)
        return {"violation_max": jnp.max(v), "violation_mean": jnp.mean(v)}

    # The ambient (storage) dimension for an n-dim manifold; Lorentz uses n+1.
    def ambient_dim(self, dim: int) -> int:
        return dim

    # --- origin coordinate chart ---------------------------------------------
    # Orthonormal coordinates on the tangent space at the origin, used by
    # distributions (WrappedNormal) and any code that needs an isometry
    # T_origin ≅ R^n.  Defaults are correct for manifolds whose origin
    # tangent space is R^n with the standard metric (Euclidean).

    def coord_dim(self, ambient_dim: int) -> int:
        """Intrinsic dimension of the origin tangent space for a given
        ambient (storage) width."""
        return ambient_dim

    def tangent_from_origin_coords(self, v: jax.Array) -> jax.Array:
        """Orthonormal origin coordinates → ambient tangent vector at the
        origin (an isometry onto T_origin)."""
        return v

    def origin_coords_from_tangent(self, u: jax.Array) -> jax.Array:
        """Inverse of :meth:`tangent_from_origin_coords`."""
        return u

    # --- expmap Jacobian (wrapped-normal density correction) ------------------
    # log |det d exp_x| in orthonormal tangent coordinates w.r.t. the
    # Riemannian volume.  Flat default (0) is exact for Euclidean space;
    # curved manifolds override both forms.

    def logdetexp(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """log-Jacobian of exp_x evaluated at log_x(y); shape [...]."""
        return jnp.zeros(jnp.broadcast_shapes(x.shape[:-1], y.shape[:-1]),
                         x.dtype)

    def logdetexp_from_coords(self, v: jax.Array) -> jax.Array:
        """Same quantity from origin-chart coordinates of the tangent whose
        norm is the geodesic radius (‖v‖ = dist(x, exp_x(transport(v)))) —
        lets samplers that already hold v skip the exp/log round-trip."""
        return jnp.zeros(v.shape[:-1], v.dtype)
