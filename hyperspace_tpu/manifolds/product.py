"""Cartesian product of manifolds with per-factor (learnable) curvature.

Semantics per Gu et al. 2019 ("Learning mixed-curvature representations in
products of model spaces") — the geometry behind reference workload 5
(BASELINE.json configs[4]: hyperbolic × spherical × Euclidean embeddings with
learned curvature, multi-host).

Points are stored concatenated along the last axis; factor i occupies the
slice ``[offset_i, offset_i + ambient_dim_i)``.  The factor manifolds are
pytree children, so their curvature leaves are traced — a product manifold
rebuilt each step from softplus-parameterized curvatures is differentiable
end-to-end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath
from hyperspace_tpu.manifolds.base import Manifold


@jax.tree_util.register_pytree_node_class
class Product(Manifold):
    name = "product"

    def __init__(self, factors, dims):
        """``factors``: manifold instances; ``dims``: ambient dim of each slice."""
        if len(factors) != len(dims):
            raise ValueError("factors and dims must have equal length")
        self.factors = tuple(factors)
        self.dims = tuple(int(d) for d in dims)
        # plain-int prefix sums: __init__ re-runs on every tree_unflatten
        # (i.e. inside every jit trace), so no device work allowed here
        offs, acc = [], 0
        for d in self.dims:
            offs.append(acc)
            acc += d
        self.offsets = tuple(offs)
        self.total_dim = acc

    def tree_flatten(self):
        return self.factors, self.dims

    @classmethod
    def tree_unflatten(cls, dims, factors):
        return cls(factors, dims)

    # --- slicing --------------------------------------------------------------

    def split(self, x: jax.Array):
        return [
            jax.lax.slice_in_dim(x, o, o + d, axis=-1)
            for o, d in zip(self.offsets, self.dims)
        ]

    def _join(self, parts):
        return jnp.concatenate(parts, axis=-1)

    def _map(self, fn_name: str, *arrays):
        parts = [self.split(a) for a in arrays]
        out = [
            getattr(m, fn_name)(*[p[i] for p in parts])
            for i, m in enumerate(self.factors)
        ]
        return self._join(out)

    # --- Manifold interface ---------------------------------------------------

    def proj(self, x):
        return self._map("proj", x)

    def proju(self, x, u):
        return self._map("proju", x, u)

    def expmap(self, x, v):
        return self._map("expmap", x, v)

    def logmap(self, x, y):
        return self._map("logmap", x, y)

    def ptransp(self, x, y, v):
        return self._map("ptransp", x, y, v)

    def egrad2rgrad(self, x, g):
        return self._map("egrad2rgrad", x, g)

    def sqdist(self, x, y):
        xs, ys = self.split(x), self.split(y)
        return sum(m.sqdist(xi, yi) for m, xi, yi in zip(self.factors, xs, ys))

    def dist(self, x, y):
        return smath.safe_sqrt(self.sqdist(x, y))

    def inner(self, x, u, v, keepdims: bool = False):
        xs, us, vs = self.split(x), self.split(u), self.split(v)
        out = sum(
            m.inner(xi, ui, vi, keepdims=True)
            for m, xi, ui, vi in zip(self.factors, xs, us, vs)
        )
        return out if keepdims else out[..., 0]

    def origin(self, shape, dtype=jnp.float32):
        assert shape[-1] == self.total_dim, (shape, self.total_dim)
        return self._join(
            [
                m.origin(shape[:-1] + (d,), dtype)
                for m, d in zip(self.factors, self.dims)
            ]
        )

    def check_point(self, x):
        return sum(m.check_point(xi) for m, xi in zip(self.factors, self.split(x)))

    def health_stats(self, x) -> dict:
        """Per-factor health merge (telemetry/health.py samples these).

        Each factor's own ``health_stats`` run on its slice, keys
        prefixed ``f<i>_<name>/`` so a 2-ball product reports both
        balls separately, PLUS unprefixed worst-case aggregates
        (min of margins, max of violations/norms, mean of means) so the
        monitor's suffix-matched thresholds fire without knowing the
        factor layout.
        """
        from hyperspace_tpu.manifolds.base import reduce_health_stats

        out: dict = {}
        per_factor = []
        for i, (m, xi) in enumerate(zip(self.factors, self.split(x))):
            stats = m.health_stats(xi)
            per_factor.append(stats)
            out.update({f"f{i}_{m.name}/{k}": v for k, v in stats.items()})
        out.update(reduce_health_stats(per_factor))
        return out

    def logdetexp(self, x, y):
        """exp on a product is the product of factor exps, so the Jacobian
        determinant factorizes: Σ factor logdetexp."""
        xs, ys = self.split(x), self.split(y)
        return sum(m.logdetexp(xi, yi) for m, xi, yi in zip(self.factors, xs, ys))

    def logdetexp_from_coords(self, v: jax.Array) -> jax.Array:
        out, o = 0, 0
        for m, d in zip(self.factors, self.dims):
            cd = m.coord_dim(d)
            out = out + m.logdetexp_from_coords(
                jax.lax.slice_in_dim(v, o, o + cd, axis=-1))
            o += cd
        return out

    def coord_dim(self, ambient_dim: int) -> int:
        assert ambient_dim == self.total_dim
        return sum(m.coord_dim(d) for m, d in zip(self.factors, self.dims))

    def tangent_from_origin_coords(self, v: jax.Array) -> jax.Array:
        parts, o = [], 0
        for m, d in zip(self.factors, self.dims):
            cd = m.coord_dim(d)
            parts.append(m.tangent_from_origin_coords(
                jax.lax.slice_in_dim(v, o, o + cd, axis=-1)))
            o += cd
        return self._join(parts)

    def origin_coords_from_tangent(self, u: jax.Array) -> jax.Array:
        return self._join([
            m.origin_coords_from_tangent(ui)
            for m, ui in zip(self.factors, self.split(u))
        ])

    def random_normal(self, key, shape, dtype=jnp.float32, std: float = 1.0):
        assert shape[-1] == self.total_dim
        keys = jax.random.split(key, len(self.factors))
        return self._join(
            [
                m.random_normal(k, shape[:-1] + (d,), dtype, std)
                for m, d, k in zip(self.factors, self.dims, keys)
            ]
        )
