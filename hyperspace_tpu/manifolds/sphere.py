"""Sphere of curvature +c (c > 0): radius-1/√c sphere embedded in R^d.

Needed for the mixed-curvature product spaces of reference workload 5
(Gu et al. 2019; BASELINE.json configs[4]).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath
from hyperspace_tpu.manifolds.base import Manifold


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Sphere(Manifold):
    c: Any = 1.0
    name = "sphere"

    def tree_flatten(self):
        return (self.c,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def _c(self, dtype) -> jax.Array:
        return jnp.asarray(self.c, dtype)

    def proj(self, x: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        r = 1.0 / smath.clamp_min(smath.sqrt_c(c), smath.min_norm(x.dtype))
        n = smath.clamp_min(smath.safe_norm(x), smath.min_norm(x.dtype))
        return x / n * r

    def proju(self, x: jax.Array, u: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        return u - c * jnp.sum(x * u, axis=-1, keepdims=True) * x

    def check_point(self, x: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        return jnp.abs(c * smath.sq_norm(x, keepdims=False) - 1.0)

    def dist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        # Chord form 2/√c·arcsin(√c‖x−y‖/2): exact at coincident points,
        # unlike arccos(c⟨x,y⟩) whose clamp floors the distance at ~1e-3.
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        chord = smath.safe_norm(x - y, keepdims=False)
        return 2.0 / sc * smath.arcsin_safe(sc * chord / 2.0)

    def sqdist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        return self.dist(x, y) ** 2

    def expmap(self, x: jax.Array, v: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        vn = smath.safe_norm(v)
        t = sc * vn
        return self.proj(jnp.cos(t) * x + smath.sinc_(t) * v)

    def logmap(self, x: jax.Array, y: jax.Array) -> jax.Array:
        w = self.proju(x, y - x)
        wn = smath.clamp_min(smath.safe_norm(w), smath.min_norm(x.dtype))
        d = self.dist(x, y)[..., None]
        return d * w / wn

    def inner(self, x: jax.Array, u: jax.Array, v: jax.Array, keepdims: bool = False) -> jax.Array:
        out = jnp.sum(u * v, axis=-1, keepdims=True)
        return out if keepdims else out[..., 0]

    def ptransp(self, x: jax.Array, y: jax.Array, v: jax.Array) -> jax.Array:
        """Transport along the geodesic x→y (Gram-Schmidt form)."""
        logxy = self.logmap(x, y)
        logyx = self.logmap(y, x)
        d2 = smath.clamp_min(self.sqdist(x, y)[..., None], smath.eps_for(x.dtype))
        return v - jnp.sum(logxy * v, axis=-1, keepdims=True) / d2 * (logxy + logyx)

    def egrad2rgrad(self, x: jax.Array, g: jax.Array) -> jax.Array:
        return self.proju(x, g)

    def origin(self, shape, dtype=jnp.float32) -> jax.Array:
        c = self._c(dtype)
        o = jnp.zeros(shape, dtype)
        return o.at[..., 0].set(1.0 / smath.sqrt_c(c))

    def logdetexp(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """log |det d exp_x|: (d−1)·log( sin(√c r)/(√c r) ), r = dist —
        the positive-curvature twin of the hyperbolic sinhc form."""
        d = x.shape[-1] - 1  # manifold dim; ambient is d+1
        r = self.dist(x, y)
        c = self._c(x.dtype)
        return (d - 1) * jnp.log(smath.clamp_min(
            smath.sinc_(smath.sqrt_c(c) * r), smath.eps_for(x.dtype)))

    def logdetexp_from_coords(self, v: jax.Array) -> jax.Array:
        c = self._c(v.dtype)
        r = smath.safe_norm(v, keepdims=False)
        return (v.shape[-1] - 1) * jnp.log(smath.clamp_min(
            smath.sinc_(smath.sqrt_c(c) * r), smath.eps_for(v.dtype)))

    # --- origin coordinate chart ---------------------------------------------
    # Tangents at the origin (1/√c, 0, …) have first coordinate 0 and the
    # standard Euclidean metric on the rest: pad/strip the first coordinate.

    def coord_dim(self, ambient_dim: int) -> int:
        return ambient_dim - 1

    def tangent_from_origin_coords(self, v: jax.Array) -> jax.Array:
        return jnp.concatenate([jnp.zeros_like(v[..., :1]), v], axis=-1)

    def origin_coords_from_tangent(self, u: jax.Array) -> jax.Array:
        return u[..., 1:]
