"""L0: pure-JAX manifold math (SURVEY.md §1b)."""

from hyperspace_tpu.manifolds import smath  # noqa: F401
from hyperspace_tpu.manifolds.base import Manifold  # noqa: F401
from hyperspace_tpu.manifolds.euclidean import Euclidean  # noqa: F401
from hyperspace_tpu.manifolds.lorentz import Lorentz, minkowski_dot  # noqa: F401
from hyperspace_tpu.manifolds.maps import (  # noqa: F401
    ball_tangent_to_lorentz,
    ball_to_lorentz,
    lorentz_tangent_to_ball,
    lorentz_to_ball,
)
from hyperspace_tpu.manifolds.poincare import PoincareBall  # noqa: F401
from hyperspace_tpu.manifolds.product import Product  # noqa: F401
from hyperspace_tpu.manifolds.sphere import Sphere  # noqa: F401
