"""Diffeomorphisms between the Poincaré ball and the Lorentz hyperboloid.

Both curvature-(-c) models appear in the reference workloads (BASELINE.json:
Poincaré embeddings on the ball, HGCN/HyboNet on the Lorentz model), so the
stereographic projection between them is a first-class op.  Distances are
preserved exactly; tests assert the round trip and the isometry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath


def lorentz_to_ball(x: jax.Array, c) -> jax.Array:
    """Stereographic projection hyperboloid → ball (drops the time coord).

    y = x_space / (1 + √c · x_0).
    """
    c = jnp.asarray(c, x.dtype)
    sc = smath.sqrt_c(c)
    denom = smath.clamp_min(1.0 + sc * x[..., :1], smath.eps_for(x.dtype))
    return x[..., 1:] / denom


def ball_to_lorentz(y: jax.Array, c) -> jax.Array:
    """Inverse stereographic projection ball → hyperboloid.

    x_0 = (1/√c)(1 + c‖y‖²)/(1 − c‖y‖²),  x_space = 2y/(1 − c‖y‖²).
    """
    c = jnp.asarray(c, y.dtype)
    sc = smath.sqrt_c(c)
    y2 = smath.sq_norm(y)
    denom = smath.clamp_min(1.0 - c * y2, smath.eps_for(y.dtype))
    x0 = (1.0 + c * y2) / (sc * denom)
    xs = 2.0 * y / denom
    return jnp.concatenate([x0, xs], axis=-1)


def lorentz_tangent_to_ball(x: jax.Array, v: jax.Array, c) -> jax.Array:
    """Pushforward of the projection differential at x applied to tangent v."""
    return jax.jvp(lambda p: lorentz_to_ball(p, c), (x,), (v,))[1]


def ball_tangent_to_lorentz(y: jax.Array, u: jax.Array, c) -> jax.Array:
    return jax.jvp(lambda p: ball_to_lorentz(p, c), (y,), (u,))[1]
