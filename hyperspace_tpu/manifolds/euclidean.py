"""Flat Euclidean factor (curvature 0), for mixed-curvature product spaces."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath
from hyperspace_tpu.manifolds.base import Manifold


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Euclidean(Manifold):
    name = "euclidean"
    c = 0.0  # curvature, for API uniformity with the curved manifolds

    def tree_flatten(self):
        return (), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls()

    def proj(self, x):
        return x

    def proju(self, x, u):
        return u

    def expmap(self, x, v):
        return x + v

    def logmap(self, x, y):
        return y - x

    def sqdist(self, x, y):
        return smath.sq_norm(y - x, keepdims=False)

    def dist(self, x, y):
        return smath.safe_norm(y - x, keepdims=False)

    def inner(self, x, u, v, keepdims: bool = False):
        out = jnp.sum(u * v, axis=-1, keepdims=True)
        return out if keepdims else out[..., 0]

    def ptransp(self, x, y, v):
        return v

    def egrad2rgrad(self, x, g):
        return g

    def origin(self, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    def random_normal(self, key, shape, dtype=jnp.float32, std: float = 1.0):
        return std * jax.random.normal(key, shape, dtype)
