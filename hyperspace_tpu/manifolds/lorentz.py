"""Lorentz (hyperboloid) model of curvature -c (c > 0).

Math follows Nickel & Kiela 2018 and Law et al. 2019 (SURVEY.md §2).  Points
live on { x ∈ R^{d+1} : ⟨x,x⟩_L = -1/c, x_0 > 0 } with the Minkowski bilinear
form ⟨x,y⟩_L = -x_0 y_0 + Σ_{i≥1} x_i y_i.  The hyperboloid is the preferred
internal representation on TPU: its ops are dominated by dot products (MXU
friendly) and it avoids the Poincaré boundary, which matters in f32/bf16
(SURVEY.md §7 "hard parts #1": prefer Lorentz internally where allowed).

Storage convention: the ambient dimension is d+1 for a d-dimensional
manifold; index 0 is the time coordinate.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath
from hyperspace_tpu.manifolds.base import Manifold


def minkowski_dot(x: jax.Array, y: jax.Array, keepdims: bool = True) -> jax.Array:
    """⟨x, y⟩_L over the last axis."""
    res = jnp.sum(x[..., 1:] * y[..., 1:], axis=-1, keepdims=True) - x[..., :1] * y[..., :1]
    return res if keepdims else res[..., 0]


def _pad_last(x: jax.Array, lo: int, hi: int) -> jax.Array:
    """Zero-pad the last axis by (lo, hi) — the time-coordinate
    assembly primitive.  Every Lorentz lift/split used to be a
    ``jnp.concatenate``; jax 0.4.37's GSPMD partitioner miscompiles
    `concatenate` whose operands are sharded over a subset of a
    multi-axis mesh (the dp×tp trap documented in
    tests/parallel/test_node_sharded.py), so the lifts are written as
    pad(+add) instead — `lax.pad` partitions cleanly.  Bitwise-equal to
    the concat form (x + 0.0 and x - 0.0 are exact), except that a
    -0.0 operand landing on a zero-padded lane comes out +0.0."""
    cfg = [(0, 0)] * (x.ndim - 1) + [(lo, hi)]
    return jnp.pad(x, cfg)


def with_time_coordinate(space: jax.Array, c) -> jax.Array:
    """Hyperboloid point from space coordinates: fix the time lane
    t = sqrt(1/c + ‖space‖²) and assemble by pad+add (the ONE home of
    the reconstruction — LorentzLinear and the attention heads route
    through it, so no Lorentz lift ever re-grows a `concatenate`)."""
    c = jnp.asarray(c, space.dtype)
    t = smath.safe_sqrt(
        1.0 / smath.clamp_min(c, smath.min_norm(space.dtype))
        + smath.sq_norm(space))
    return _pad_last(t, 0, space.shape[-1]) + _pad_last(space, 1, 0)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Lorentz(Manifold):
    c: Any = 1.0
    name = "lorentz"

    def tree_flatten(self):
        return (self.c,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def _c(self, dtype) -> jax.Array:
        return jnp.asarray(self.c, dtype)

    def ambient_dim(self, dim: int) -> int:
        return dim + 1

    # --- constraint / projections --------------------------------------------

    def proj(self, x: jax.Array) -> jax.Array:
        """Fix the time coordinate from the space coordinates."""
        return with_time_coordinate(x[..., 1:], self._c(x.dtype))

    def proju(self, x: jax.Array, u: jax.Array) -> jax.Array:
        """Tangent projection: u + c ⟨x,u⟩_L x (⟨x,x⟩_L = -1/c)."""
        c = self._c(x.dtype)
        return u + c * minkowski_dot(x, u) * x

    def check_point(self, x: jax.Array) -> jax.Array:
        # Relative residual: hyperboloid coordinates grow like e^dist, so the
        # raw ⟨x,x⟩_L + 1/c residual scales with ‖x‖² and must be normalized.
        c = self._c(x.dtype)
        scale = 1.0 / c + smath.sq_norm(x, keepdims=False)
        return jnp.abs(minkowski_dot(x, x, keepdims=False) + 1.0 / c) / scale

    def health_stats(self, x: jax.Array) -> dict:
        """Constraint-drift indicators (telemetry/health.py samples these).

        The hyperboloid's blow-up mode is ⟨x,x⟩_L drifting off −1/c
        under low-precision accumulation, which amplifies gradients
        through every arcosh/dist (Chami et al. 2019); reports the
        max/mean RELATIVE residual (``check_point``'s normalization —
        coordinates grow like e^dist, so the raw residual would scale
        with ‖x‖²) plus the max time coordinate √c·x₀ = cosh(√c·dist0),
        the cheap proxy for how far out the sheet the batch reaches.
        """
        c = self._c(x.dtype)
        v = self.check_point(x)
        return {"violation_max": jnp.max(v), "violation_mean": jnp.mean(v),
                "time_coord_max": jnp.max(smath.sqrt_c(c) * x[..., 0])}

    # --- distance -------------------------------------------------------------

    def _neg_cdot(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """u = -c⟨x,y⟩_L - 1 ≥ 0; dist = arcosh(1+u)/√c (stable form)."""
        c = self._c(x.dtype)
        return -c * minkowski_dot(x, y) - 1.0

    def dist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        u = self._neg_cdot(x, y)[..., 0]
        return smath.arcosh1p(u) / smath.sqrt_c(c)

    def sqdist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        return self.dist(x, y) ** 2

    # --- exp / log ------------------------------------------------------------

    def expmap(self, x: jax.Array, v: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        vn = smath.safe_sqrt(smath.clamp_min(minkowski_dot(v, v), 0.0))
        t = sc * vn
        # sinh(t)/(√c‖v‖_L) = sinh(t)/t = sinhc(t), smooth at v = 0.
        return self.proj(smath.safe_cosh(t) * x + smath.sinhc(t) * v)

    def logmap(self, x: jax.Array, y: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        # v = d(x,y) * (y + c⟨x,y⟩_L x) / ‖·‖_L ; smooth form via u-parameterization.
        cxy = minkowski_dot(x, y)
        w = y + c * cxy * x  # tangent direction, ⟨x,w⟩_L = 0
        wn = smath.safe_sqrt(smath.clamp_min(minkowski_dot(w, w), 0.0))
        d = self.dist(x, y)[..., None]
        return d * w / smath.clamp_min(wn, smath.min_norm(x.dtype))

    def origin(self, shape, dtype=jnp.float32) -> jax.Array:
        c = self._c(dtype)
        t = jnp.ones(shape[:-1] + (1,), dtype) / smath.sqrt_c(c)
        return _pad_last(t, 0, shape[-1] - 1)

    # --- transport / metric ---------------------------------------------------

    def inner(self, x: jax.Array, u: jax.Array, v: jax.Array, keepdims: bool = False) -> jax.Array:
        return minkowski_dot(u, v, keepdims=keepdims)

    def ptransp(self, x: jax.Array, y: jax.Array, v: jax.Array) -> jax.Array:
        """P_{x→y}(v) = v + c⟨y,v⟩_L / (1 - c⟨x,y⟩_L) (x + y)  (kernel N4)."""
        c = self._c(x.dtype)
        num = c * minkowski_dot(y, v)
        den = smath.clamp_min(1.0 - c * minkowski_dot(x, y), smath.eps_for(x.dtype))
        return v + num / den * (x + y)

    def egrad2rgrad(self, x: jax.Array, g: jax.Array) -> jax.Array:
        """Flip the time component (Minkowski metric inverse), then proju."""
        # g - 2·pad(g₀): lane 0 is g₀ - 2g₀ = -g₀ (Sterbenz: exact),
        # space lanes subtract an exact 0 — bitwise the concat form
        gl = g - 2.0 * _pad_last(g[..., :1], 0, g.shape[-1] - 1)
        return self.proju(x, gl)

    def retr(self, x: jax.Array, v: jax.Array) -> jax.Array:
        return self.proj(x + v)

    def logdetexp(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """log |det d exp_x| at log_x(y) (orthonormal coords → Riemannian
        volume): (d−1)·log(sinh(√c r)/(√c r)), r = dist (Nagano et al. 2019).
        """
        c = self._c(x.dtype)
        d = x.shape[-1] - 1  # manifold dim; ambient is d+1
        r = self.dist(x, y)
        return (d - 1) * jnp.log(smath.clamp_min(
            smath.sinhc(smath.sqrt_c(c) * r), smath.eps_for(x.dtype)))

    def logdetexp_from_coords(self, v: jax.Array) -> jax.Array:
        c = self._c(v.dtype)
        r = smath.safe_norm(v, keepdims=False)  # coords are the space part
        return (v.shape[-1] - 1) * jnp.log(smath.clamp_min(
            smath.sinhc(smath.sqrt_c(c) * r), smath.eps_for(v.dtype)))

    # --- origin coordinate chart ---------------------------------------------
    # Tangents at the origin have time coordinate 0 and carry the standard
    # Euclidean metric on the space part, so the chart is pad/strip time.

    def coord_dim(self, ambient_dim: int) -> int:
        return ambient_dim - 1

    def tangent_from_origin_coords(self, v: jax.Array) -> jax.Array:
        return _pad_last(v, 1, 0)

    def origin_coords_from_tangent(self, u: jax.Array) -> jax.Array:
        return u[..., 1:]

    # --- aggregation (used by HGCN / attention on the hyperboloid) ------------

    def centroid(self, x: jax.Array, w: jax.Array | None = None) -> jax.Array:
        """Lorentz centroid (Law et al. 2019): normalize the weighted sum.

        x: [..., n, d+1]; w: [..., n] (uniform if None).
        μ = s / (√c · √(-⟨s,s⟩_L)) with s = Σ w_i x_i.
        """
        c = self._c(x.dtype)
        if w is None:
            s = jnp.sum(x, axis=-2)
        else:
            s = jnp.sum(w[..., None] * x, axis=-2)
        nrm = smath.safe_sqrt(smath.clamp_min(-minkowski_dot(s, s), smath.eps_for(x.dtype)))
        return s / (smath.sqrt_c(c) * nrm)

