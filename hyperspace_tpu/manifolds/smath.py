"""Numerically-stable scalar math shared by every manifold.

TPUs have no float64, so the boundary behaviour of the hyperbolic functions
(artanh near ±1, arcosh near 1, x/‖x‖ near 0) must be handled explicitly:
every potentially-singular scalar op here has a clamped primal and a bounded
gradient, so a jitted train step never emits NaN/Inf even in bf16
(SURVEY.md §7 "hard parts #1").

Conventions:
- Hyperbolic manifolds carry a positive scalar ``c`` (curvature magnitude;
  sectional curvature is ``-c``). Spherical manifolds also carry positive
  ``c`` (sectional curvature ``+c``). ``c`` may be a traced JAX scalar, so
  curvature can be learned (reference workload 5, BASELINE.json configs[4]).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# --- dtype-dependent epsilons -------------------------------------------------

_MIN_NORM = 1e-15


def eps_for(dtype) -> float:
    """A general-purpose small epsilon for the given float dtype."""
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return 1e-12
    if dt == jnp.float32:
        return 1e-7
    return 1e-4  # bfloat16 / float16


def ball_eps(dtype) -> float:
    """Distance kept between a projected point and the ball boundary."""
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return 1e-5
    if dt == jnp.float32:
        return 4e-3
    return 1e-2


def min_norm(dtype) -> float:
    """Smallest norm used as a division guard."""
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return _MIN_NORM
    if dt == jnp.float32:
        return 1e-12
    return 1e-7


# --- guarded elementary functions --------------------------------------------


def clamp_min(x: jax.Array, m) -> jax.Array:
    return jnp.maximum(x, m)


@jax.custom_jvp
def safe_sqrt(x: jax.Array) -> jax.Array:
    """sqrt with a zero-clamped primal and a bounded gradient at 0."""
    return jnp.sqrt(jnp.maximum(x, 0.0))


@safe_sqrt.defjvp
def _safe_sqrt_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    y = safe_sqrt(x)
    denom = jnp.maximum(2.0 * y, 2.0 * jnp.sqrt(jnp.asarray(eps_for(y.dtype), y.dtype)))
    return y, t / denom


def sq_norm(x: jax.Array, keepdims: bool = True) -> jax.Array:
    return jnp.sum(x * x, axis=-1, keepdims=keepdims)


def safe_norm(x: jax.Array, keepdims: bool = True) -> jax.Array:
    """L2 norm over the last axis; gradient is finite at x = 0."""
    return safe_sqrt(sq_norm(x, keepdims=keepdims))


def _artanh_eps(dtype) -> float:
    # A few ulps below 1: tight enough not to distort representable
    # distances, loose enough to bound the gradient at the boundary.
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return 1e-12
    if dt == jnp.float32:
        return 3e-7
    return 1e-2


def artanh(x: jax.Array) -> jax.Array:
    """arctanh with the argument clamped into the open interval (-1, 1).

    The clamp bounds the gradient instead of letting it diverge at the
    boundary — the dominant failure mode of Poincaré math in float32.
    """
    e = _artanh_eps(x.dtype)
    return jnp.arctanh(jnp.clip(x, -1.0 + e, 1.0 - e))


def arcosh1p(u: jax.Array) -> jax.Array:
    """arcosh(1 + u) for u >= 0, numerically stable near u = 0.

    arcosh(1+u) = log1p(u + sqrt(u (u + 2))).  Using ``safe_sqrt`` keeps the
    gradient finite at u = 0 (coincident points in the Lorentz distance).
    """
    u = jnp.maximum(u, 0.0)
    return jnp.log1p(u + safe_sqrt(u * (u + 2.0)))


def arcsin_safe(x: jax.Array) -> jax.Array:
    """arcsin clamped into the open interval so the gradient stays bounded."""
    e = _artanh_eps(x.dtype)
    return jnp.arcsin(jnp.clip(x, -1.0 + e, 1.0 - e))


def exp_arg_max(dtype) -> float:
    """Largest |t| fed to cosh/sinh (results must survive a later square)."""
    return 350.0 if jnp.dtype(dtype) == jnp.float64 else 40.0


def safe_tanh(x: jax.Array) -> jax.Array:
    """tanh with the argument clipped to ±20.

    tanh saturates to 1 within 4e-18 by |x|=20, and this XLA build's f64 tanh
    returns NaN for large arguments (observed: tanh(124.)→nan), so the clip is
    both an accuracy no-op and a hard NaN guard.
    """
    return jnp.tanh(jnp.clip(x, -20.0, 20.0))


def safe_cosh(x: jax.Array) -> jax.Array:
    m = exp_arg_max(x.dtype)
    return jnp.cosh(jnp.clip(x, -m, m))


def safe_sinh(x: jax.Array) -> jax.Array:
    m = exp_arg_max(x.dtype)
    return jnp.sinh(jnp.clip(x, -m, m))


def sinhc(x: jax.Array) -> jax.Array:
    """sinh(x)/x, smooth at x = 0 (Taylor branch below a dtype threshold)."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, jnp.ones_like(x), x)  # double-where: keep grads NaN-free
    return jnp.where(small, 1.0 + x * x / 6.0, safe_sinh(xs) / jnp.clip(xs, -exp_arg_max(x.dtype), exp_arg_max(x.dtype)))


def sinc_(x: jax.Array) -> jax.Array:
    """sin(x)/x, smooth at x = 0."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, jnp.ones_like(x), x)
    return jnp.where(small, 1.0 - x * x / 6.0, jnp.sin(xs) / xs)


def tanc(x: jax.Array) -> jax.Array:
    """tanh(x)/x, smooth at x = 0."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, jnp.ones_like(x), x)
    return jnp.where(small, 1.0 - x * x / 3.0, safe_tanh(xs) / xs)


def artanc(x: jax.Array) -> jax.Array:
    """artanh(x)/x, smooth at x = 0 (x clamped inside (-1, 1))."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, jnp.ones_like(x), x)
    return jnp.where(small, 1.0 + x * x / 3.0, artanh(xs) / xs)


def sqrt_c(c) -> jax.Array:
    """sqrt of a (possibly traced) positive curvature magnitude."""
    return safe_sqrt(jnp.asarray(c))
