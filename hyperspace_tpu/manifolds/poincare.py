"""Poincaré ball of curvature -c (c > 0) with Möbius gyrovector operations.

Math follows Ganea et al. 2018 ("Hyperbolic Neural Networks") and Ungar's
gyrovector calculus; these fix the semantics of the reference's CUDA
primitives — Möbius add / scalar-mul, expmap/logmap, parallel transport,
gyro-linear — listed in BASELINE.json's north star (SURVEY.md §0 items 1-5).

The ball of curvature -c is { x ∈ R^d : c‖x‖² < 1 } with conformal factor
λ_x = 2 / (1 - c‖x‖²).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import smath
from hyperspace_tpu.manifolds.base import Manifold


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PoincareBall(Manifold):
    """Curvature is stored as the positive magnitude ``c`` (a pytree leaf)."""

    c: Any = 1.0
    name = "poincare"

    def tree_flatten(self):
        return (self.c,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # --- helpers --------------------------------------------------------------

    def _c(self, dtype) -> jax.Array:
        return jnp.asarray(self.c, dtype)

    def lambda_x(self, x: jax.Array, keepdims: bool = True) -> jax.Array:
        c = self._c(x.dtype)
        denom = smath.clamp_min(1.0 - c * smath.sq_norm(x), smath.eps_for(x.dtype))
        out = 2.0 / denom
        return out if keepdims else out[..., 0]

    # --- constraint / projections --------------------------------------------

    def proj(self, x: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        norm = smath.clamp_min(smath.safe_norm(x), smath.min_norm(x.dtype))
        max_norm = (1.0 - smath.ball_eps(x.dtype)) / smath.clamp_min(sc, smath.min_norm(x.dtype))
        cond = norm > max_norm
        return jnp.where(cond, x / norm * max_norm, x)

    def proju(self, x: jax.Array, u: jax.Array) -> jax.Array:
        return u  # tangent space is all of R^d

    def check_point(self, x: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        return smath.clamp_min(c * smath.sq_norm(x, keepdims=False) - 1.0, 0.0)

    def health_stats(self, x: jax.Array) -> dict:
        """Boundary-drift indicators (telemetry/health.py samples these).

        The ball's blow-up mode is points drifting to the boundary,
        where λ_x and every artanh-amplified gradient diverge (Nickel &
        Kiela 2017).  Reports the scaled radius r = √c‖x‖ ∈ [0, 1)
        (max/mean over the batch) and the minimum distance-to-boundary
        margin 1 − r — ``proj`` clamps f32 points to a margin of
        ``ball_eps(f32) = 4e-3``, so a point pinned at the clamp reads
        as margin ≈ 4e-3, well under the monitor's default warn
        threshold of 1e-2.
        """
        c = self._c(x.dtype)
        r = smath.sqrt_c(c) * smath.safe_norm(x, keepdims=False)
        r_max = jnp.max(r)
        return {"norm_max": r_max, "norm_mean": jnp.mean(r),
                "boundary_margin_min": 1.0 - r_max}

    # --- Möbius gyrovector ops (reference native kernels N1/N2) ---------------

    def mobius_add(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """x ⊕_c y (reference CUDA kernel N1; SURVEY.md §2)."""
        c = self._c(x.dtype)
        x2 = smath.sq_norm(x)
        y2 = smath.sq_norm(y)
        xy = jnp.sum(x * y, axis=-1, keepdims=True)
        num = (1.0 + 2.0 * c * xy + c * y2) * x + (1.0 - c * x2) * y
        denom = 1.0 + 2.0 * c * xy + (c ** 2) * x2 * y2
        return num / smath.clamp_min(denom, smath.eps_for(x.dtype))

    def mobius_neg(self, x: jax.Array) -> jax.Array:
        return -x

    def mobius_scalar_mul(self, r, x: jax.Array) -> jax.Array:
        """r ⊗_c x (reference CUDA kernel N2)."""
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        norm = smath.clamp_min(smath.safe_norm(x), smath.min_norm(x.dtype))
        t = smath.safe_tanh(r * smath.artanh(sc * norm))
        return t * x / smath.clamp_min(sc * norm, smath.min_norm(x.dtype))

    def mobius_matvec(self, m: jax.Array, x: jax.Array) -> jax.Array:
        """M ⊗_c x: the linear part of the gyro-linear layer (kernel N5).

        ``m`` has shape [d_in, d_out]; applied on the last axis of ``x``.
        """
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        x_norm = smath.clamp_min(smath.safe_norm(x), smath.min_norm(x.dtype))
        # HIGHEST: the matmul feeds tanh∘artanh-amplified norms; the default
        # bf16-pass TPU matmul costs ~2e-3 absolute on ball points
        mx = jnp.matmul(x, m, precision=jax.lax.Precision.HIGHEST)
        mx_norm = smath.clamp_min(smath.safe_norm(mx), smath.min_norm(x.dtype))
        sc = smath.clamp_min(sc, smath.min_norm(x.dtype))  # guard learned c → 0
        res = smath.safe_tanh(mx_norm / x_norm * smath.artanh(sc * x_norm)) * mx / (mx_norm * sc)
        # M x = 0 maps to the origin (gyro-linearity convention).
        zero = jnp.all(mx == 0.0, axis=-1, keepdims=True)
        return jnp.where(zero, jnp.zeros_like(res), res)

    def gyration(self, u: jax.Array, v: jax.Array, w: jax.Array) -> jax.Array:
        """gyr[u, v] w — closed form (Ungar), avoids three Möbius additions."""
        c = self._c(u.dtype)
        u2 = smath.sq_norm(u)
        v2 = smath.sq_norm(v)
        uv = jnp.sum(u * v, axis=-1, keepdims=True)
        uw = jnp.sum(u * w, axis=-1, keepdims=True)
        vw = jnp.sum(v * w, axis=-1, keepdims=True)
        c2 = c ** 2
        a = -c2 * uw * v2 + c * vw + 2.0 * c2 * uv * vw
        b = -c2 * vw * u2 - c * uw
        d = 1.0 + 2.0 * c * uv + c2 * u2 * v2
        return w + 2.0 * (a * u + b * v) / smath.clamp_min(d, smath.eps_for(u.dtype))

    # --- exp / log / distance (reference kernel N3) ---------------------------

    def expmap(self, x: jax.Array, v: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        v_norm = smath.safe_norm(v)
        lam = self.lambda_x(x)
        t = sc * lam * v_norm / 2.0
        second = smath.tanc(t) * lam / 2.0 * v  # tanh(t)/t · (λ/2) v — smooth at v=0
        return self.proj(self.mobius_add(x, second))

    def logmap(self, x: jax.Array, y: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        sub = self.mobius_add(-x, y)
        sub_norm = smath.safe_norm(sub)
        lam = self.lambda_x(x)
        # (2/(√c λ)) artanh(√c‖sub‖) sub/‖sub‖, smooth at y = x via artanc.
        return (2.0 / lam) * smath.artanc(sc * sub_norm) * sub

    def expmap0(self, v: jax.Array) -> jax.Array:
        c = self._c(v.dtype)
        sc = smath.sqrt_c(c)
        v_norm = smath.safe_norm(v)
        return self.proj(smath.tanc(sc * v_norm) * v)

    def logmap0(self, y: jax.Array) -> jax.Array:
        c = self._c(y.dtype)
        sc = smath.sqrt_c(c)
        y_norm = smath.safe_norm(y)
        return smath.artanc(sc * y_norm) * y

    def sqdist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        return self.dist(x, y) ** 2

    def dist(self, x: jax.Array, y: jax.Array) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.sqrt_c(c)
        diff_norm = smath.safe_norm(self.mobius_add(-x, y), keepdims=False)
        return 2.0 / smath.clamp_min(sc, smath.min_norm(x.dtype)) * smath.artanh(sc * diff_norm)

    def dist0(self, x: jax.Array, keepdims: bool = False) -> jax.Array:
        c = self._c(x.dtype)
        sc = smath.clamp_min(smath.sqrt_c(c), smath.min_norm(x.dtype))
        return 2.0 / sc * smath.artanh(sc * smath.safe_norm(x, keepdims=keepdims))

    # --- transport / metric ---------------------------------------------------

    def inner(self, x: jax.Array, u: jax.Array, v: jax.Array, keepdims: bool = False) -> jax.Array:
        lam = self.lambda_x(x)
        out = lam ** 2 * jnp.sum(u * v, axis=-1, keepdims=True)
        return out if keepdims else out[..., 0]

    def ptransp(self, x: jax.Array, y: jax.Array, v: jax.Array) -> jax.Array:
        """P_{x→y}(v) = (λ_x / λ_y) gyr[y, -x] v (reference kernel N4)."""
        return self.gyration(y, -x, v) * self.lambda_x(x) / self.lambda_x(y)

    def egrad2rgrad(self, x: jax.Array, g: jax.Array) -> jax.Array:
        return g / self.lambda_x(x) ** 2

    def origin(self, shape, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros(shape, dtype)

    # --- origin coordinate chart ---------------------------------------------
    # The metric at 0 is λ₀² δ = 4 δ (independent of c), so orthonormal
    # coordinates differ from ambient tangents by the factor λ₀ = 2.

    def tangent_from_origin_coords(self, v: jax.Array) -> jax.Array:
        return v / 2.0

    def origin_coords_from_tangent(self, u: jax.Array) -> jax.Array:
        return u * 2.0

    def logdetexp(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """log |det d exp_x| at log_x(y), w.r.t. orthonormal tangent coords
        and the Riemannian volume: (d−1)·log( sinh(√c r)/(√c r) ), r=dist.

        The Jacobian correction of the wrapped-normal density (Nagano 2019 /
        Mathieu 2019; SURVEY.md §2 "WrappedNormal").
        """
        c = self._c(x.dtype)
        d = x.shape[-1]
        r = self.dist(x, y)
        return (d - 1) * jnp.log(smath.clamp_min(
            smath.sinhc(smath.sqrt_c(c) * r), smath.eps_for(x.dtype)))

    def logdetexp_from_coords(self, v: jax.Array) -> jax.Array:
        c = self._c(v.dtype)
        r = smath.safe_norm(v, keepdims=False)
        return (v.shape[-1] - 1) * jnp.log(smath.clamp_min(
            smath.sinhc(smath.sqrt_c(c) * r), smath.eps_for(v.dtype)))

    # --- gyro extras used by models ------------------------------------------

    def gyromidpoint(self, x: jax.Array, w: jax.Array | None = None) -> jax.Array:
        """Möbius gyromidpoint over the second-to-last axis with weights ``w``.

        x: [..., n, d]; w: [..., n] (defaults to uniform). Used by hyperbolic
        attention aggregation (reference kernel N7 semantics, Gulcehre 2019).
        """
        c = self._c(x.dtype)
        lam = self.lambda_x(x)  # [..., n, 1]
        if w is None:
            w = jnp.ones(x.shape[:-1], x.dtype)
        w = w[..., None]
        num = jnp.sum(w * lam * x, axis=-2)
        den = smath.clamp_min(
            jnp.abs(jnp.sum(w * (lam - 1.0), axis=-2)), smath.eps_for(x.dtype)
        )
        return self.proj(self.mobius_scalar_mul(0.5, num / den))
