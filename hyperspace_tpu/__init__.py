"""hyperspace_tpu — a TPU-native Riemannian-geometry deep-learning framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of the reference
``fbad/hyperspace`` framework (CUDA + NCCL; see /root/repo/SURVEY.md for the
evidence map): hyperbolic manifold math (Poincaré ball + Lorentz model, plus
Sphere/Euclidean/Product for mixed-curvature spaces), Riemannian SGD/Adam as
single XLA-compiled train steps, Pallas TPU kernels for the hot primitives,
and GSPMD sharding over a device mesh in place of NCCL all-reduce.

Layer map (SURVEY.md §1b):
  manifolds/  L0 pure-JAX manifold math (curvature is a traced value)
  kernels/    L1 Pallas TPU kernels + pure-JAX twins (fallback & test oracle)
  optim/      L2 Riemannian SGD / Adam (optax-style transforms)
  nn/         L3 hyperbolic layers (HypLinear, LorentzLinear, attention, ...)
  train/      L4 jitted train loop, Mesh/GSPMD sharding, checkpointing
  models/     L5 the five reference workloads
  data/       loaders (WordNet closure, graphs, MNIST, text)
  serve/      inference: frozen serving artifacts + batched query engine
"""

__version__ = "0.1.0"

from hyperspace_tpu.manifolds import (  # noqa: F401
    Euclidean,
    Lorentz,
    PoincareBall,
    Product,
    Sphere,
)
