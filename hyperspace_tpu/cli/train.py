"""Single training entry point: ``python -m hyperspace_tpu.cli.train``.

SURVEY.md §5 "Config/flag system": typed dataclass configs, one per
workload (the five BASELINE.json configs), overridable from YAML and
``key=value`` CLI args; a config fully determines mesh, model, data and
optimizer — no hidden globals.

    python -m hyperspace_tpu.cli.train poincare steps=500 dim=10
    python -m hyperspace_tpu.cli.train hgcn task=lp dataset=cora
    python -m hyperspace_tpu.cli.train hybonet --yaml exp.yaml
    python -m hyperspace_tpu.cli.train hvae steps=200
    python -m hyperspace_tpu.cli.train product multihost=true

Each run writes JSONL metrics (``--log``), optional orbax checkpoints
(``--ckpt-dir``), and prints one final JSON line of results.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


# --- run-level options (shared across workloads) ------------------------------


@dataclasses.dataclass
class RunConfig:
    steps: int = 500
    seed: int = 0
    eval_every: int = 0  # 0 = eval only at the end
    log: str | None = None  # JSONL path
    tensorboard_dir: str | None = None  # optional TB sink (process 0 only)
    ckpt_dir: str | None = None
    ckpt_every: int = 100
    resume: bool = False
    data_root: str | None = None  # on-disk dataset directory
    multihost: bool = False  # jax.distributed.initialize + host mesh axis
    coordinator: str = "127.0.0.1:9357"
    num_processes: int = 1
    process_id: int = 0


def _coerce(old: Any, s: str) -> Any:
    if old is None:
        return s
    t = type(old)
    if t is bool:
        return s.lower() in ("1", "true", "yes")
    if dataclasses.is_dataclass(old):
        raise ValueError("cannot override nested config directly")
    if t is tuple:
        return tuple(json.loads(s))
    try:
        return t(s)
    except (TypeError, ValueError):
        return s


def apply_overrides(cfg, overrides: dict[str, str]):
    """Apply {field: str} overrides to a (frozen) dataclass, coercing types."""
    updates = {}
    names = {f.name: f for f in dataclasses.fields(cfg)}
    for k, v in overrides.items():
        if k not in names:
            raise SystemExit(
                f"unknown option {k!r} for {type(cfg).__name__}; "
                f"known: {sorted(names)}")
        updates[k] = _coerce(getattr(cfg, k), v)
    return dataclasses.replace(cfg, **updates)


def split_overrides(pairs: list[str], run: RunConfig):
    """Partition key=value args into (run-config updates, workload updates)."""
    run_names = {f.name for f in dataclasses.fields(RunConfig)}
    run_kv, wl_kv = {}, {}
    for p in pairs:
        if "=" not in p:
            raise SystemExit(f"expected key=value, got {p!r}")
        k, v = p.split("=", 1)
        (run_kv if k in run_names else wl_kv)[k] = v
    return apply_overrides(run, run_kv), wl_kv


# --- workload runners ---------------------------------------------------------


def run_poincare(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import wordnet
    from hyperspace_tpu.models import poincare_embed as pe

    if run.data_root:
        ds = wordnet.load_closure_tsv(run.data_root)
    else:
        ds = wordnet.synthetic_tree(depth=5, branching=4)
    cfg = apply_overrides(
        pe.PoincareEmbedConfig(num_nodes=ds.num_nodes), overrides)
    state, opt = pe.init_state(cfg, run.seed)
    pairs = jnp.asarray(ds.pairs)
    with _logger(run) as log:
        for i in range(run.steps):
            state, loss = pe.train_step(cfg, opt, state, pairs)
            _maybe_log(log, run, i, loss)
    res = pe.evaluate(state.table, ds.pairs, cfg.c)
    return {"workload": "poincare", "steps": run.steps, **res}


def run_hgcn(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    task = overrides.pop("task", "lp")
    dataset = overrides.pop("dataset", "cora")
    edges, x, labels, ncls, source = G.load_graph(dataset, run.data_root)
    cfg = apply_overrides(
        hgcn.HGCNConfig(feat_dim=x.shape[1],
                        num_classes=ncls if task == "nc" else 0),
        overrides)
    if task == "lp":
        split = G.split_edges(edges, x.shape[0], x, seed=run.seed)
        model, params, _ = hgcn.train_lp(cfg, split, steps=run.steps, seed=run.seed)
        res = hgcn.evaluate_lp(model, params, split, "test")
    else:
        tr, va, te = G.node_split_masks(x.shape[0], seed=run.seed)
        g = G.prepare(edges, x.shape[0], x, labels=labels, num_classes=ncls,
                      train_mask=tr, val_mask=va, test_mask=te)
        model, params, res = hgcn.train_nc(cfg, g, steps=run.steps, seed=run.seed)
    return {"workload": "hgcn", "task": task, "dataset": dataset,
            "source": source, **res}


def run_hybonet(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import text as T
    from hyperspace_tpu.models import hybonet

    dataset = overrides.pop("dataset", "text")
    ds, source = T.load_text(dataset, run.data_root)
    tr, te = ds.split(0.8, seed=run.seed)
    cfg = apply_overrides(
        hybonet.HyboNetConfig(vocab_size=ds.vocab_size,
                              num_classes=ds.num_classes,
                              max_len=ds.tokens.shape[1]),
        overrides)
    model, params, loss = hybonet.train(cfg, tr, steps=run.steps, seed=run.seed)
    res = hybonet.evaluate(model, params, te)
    return {"workload": "hybonet", "source": source, "loss": loss, **res}


def run_hvae(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import mnist as M
    from hyperspace_tpu.models import hvae

    ds, source = M.load_mnist(run.data_root)
    cfg = apply_overrides(hvae.HVAEConfig(image_size=ds.images.shape[1]), overrides)
    model, state, metrics = hvae.train(cfg, ds.images, steps=run.steps, seed=run.seed)
    x = jnp.asarray(ds.images[:256], cfg.dtype)
    iwae = float(hvae.iwae_bound(model, state.params, x, jax.random.PRNGKey(1), k=16))
    return {"workload": "hvae", "source": source, **metrics, "iwae": iwae}


def run_product(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import wordnet
    from hyperspace_tpu.models import product_embed as pme
    from hyperspace_tpu.parallel.mesh import make_mesh, multihost_mesh

    if run.data_root:
        ds = wordnet.load_closure_tsv(run.data_root)
    else:
        ds = wordnet.synthetic_tree(depth=5, branching=3)
    cfg = apply_overrides(
        pme.ProductEmbedConfig(num_nodes=ds.num_nodes), overrides)
    state, curv_opt = pme.init_state(cfg, run.seed)
    pairs = jnp.asarray(ds.pairs)
    if run.multihost:
        mesh = multihost_mesh()
        step = pme.make_sharded_step(cfg, curv_opt, mesh)
        stepper = lambda st: step(st, pairs)
    elif len(jax.devices()) > 1:
        mesh = make_mesh({"data": len(jax.devices())})
        step = pme.make_sharded_step(cfg, curv_opt, mesh)
        stepper = lambda st: step(st, pairs)
    else:
        stepper = lambda st: pme.train_step(cfg, curv_opt, state=st, pairs=pairs)
    with _logger(run) as log:
        for i in range(run.steps):
            state, loss = stepper(state)
            _maybe_log(log, run, i, loss)
    res = pme.evaluate(cfg, state.params, ds.pairs)
    return {"workload": "product", **res,
            "curvatures": pme.curvatures(cfg, state.params)}


WORKLOADS = {
    "poincare": run_poincare,
    "hgcn": run_hgcn,
    "hybonet": run_hybonet,
    "hvae": run_hvae,
    "product": run_product,
}


# --- helpers ------------------------------------------------------------------


def _logger(run: RunConfig):
    from hyperspace_tpu.train.logging import MetricsLogger

    return MetricsLogger(run.log, stdout=False,
                         tensorboard_dir=run.tensorboard_dir)


def _maybe_log(log, run: RunConfig, step: int, loss):
    every = run.eval_every or 50
    if (step + 1) % every == 0:
        log.log(step + 1, loss=float(loss))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hyperspace_tpu.cli.train",
        description="Train a hyperspace-tpu workload.")
    ap.add_argument("workload", choices=sorted(WORKLOADS))
    ap.add_argument("overrides", nargs="*",
                    help="key=value overrides (run- or workload-config)")
    ap.add_argument("--yaml", default=None,
                    help="YAML file of overrides (CLI wins on conflict)")
    args = ap.parse_args(argv)

    pairs = []
    if args.yaml:
        import yaml

        with open(args.yaml) as f:
            doc = yaml.safe_load(f) or {}
        pairs += [f"{k}={json.dumps(v) if isinstance(v, list) else v}"
                  for k, v in doc.items()]
    pairs += args.overrides

    run, wl_overrides = split_overrides(pairs, RunConfig())
    if run.multihost:
        jax.distributed.initialize(
            coordinator_address=run.coordinator,
            num_processes=run.num_processes,
            process_id=run.process_id)
    result = WORKLOADS[args.workload](run, wl_overrides)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
