"""Single training entry point: ``python -m hyperspace_tpu.cli.train``.

SURVEY.md §5 "Config/flag system": typed dataclass configs, one per
workload (the five BASELINE.json configs), overridable from YAML and
``key=value`` CLI args; a config fully determines mesh, model, data and
optimizer — no hidden globals.

    python -m hyperspace_tpu.cli.train poincare steps=500 dim=10
    python -m hyperspace_tpu.cli.train hgcn task=lp dataset=cora
    python -m hyperspace_tpu.cli.train hybonet --yaml exp.yaml
    python -m hyperspace_tpu.cli.train hvae steps=200
    python -m hyperspace_tpu.cli.train product multihost=true

Each run writes JSONL metrics (``--log``), optional orbax checkpoints
(``--ckpt-dir``), and prints one final JSON line of results.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


# --- run-level options (shared across workloads) ------------------------------


@dataclasses.dataclass
class RunConfig:
    steps: int = 500
    seed: int = 0
    eval_every: int = 0  # 0 = eval only at the end
    log: str | None = None  # JSONL path
    tensorboard_dir: str | None = None  # optional TB sink (process 0 only)
    ckpt_dir: str | None = None
    ckpt_every: int = 100
    resume: bool = False
    data_root: str | None = None  # on-disk dataset directory
    multihost: bool = False  # jax.distributed.initialize + host mesh axis
    tp: int = 2  # tensor-parallel degree for HGCN's auto mesh (1 = pure dp)
    # >1: run this many steps per dispatch as one lax.scan program
    # (train/loop.make_chunked_stepper; ALL workloads) — removes the
    # per-step launch latency that pins small-step workloads at the
    # dispatch floor (docs/benchmarks.md "chunked dispatch"); the step
    # budget rounds UP to a chunk multiple, and checkpoints/logs land on
    # chunk boundaries
    scan_chunk: int = 1
    # persistent on-disk graph-prep cache (data/prep_cache.py):
    # auto = cache big graphs only; true/false force on/off
    graph_cache: str = "auto"
    # >1: accumulate this many microbatch gradients per optimizer update
    # (hybonet/hvae; optax.MultiSteps — `steps` counts microsteps)
    accum: int = 1
    # mixed-precision policy preset (hyperspace_tpu/precision.py,
    # docs/precision.md): "f32" (default, bit-identical to a pre-policy
    # build) or "bf16" (compute in bf16; params, manifold boundary math
    # and reductions stay f32).  Copied into the workload config's own
    # `precision` field unless that is overridden explicitly.
    precision: str = "f32"
    # --- beyond-HBM host-resident table (poincare; docs/serving.md
    # "Beyond-HBM tables", train/host_embed.py) ------------------------
    # host_table=1: keep the packed embedding table (+ optimizer
    # moments) in HOST memory and train through a device hot-row cache
    # — per-chunk unique-id gather, one planned-sparse dispatch per
    # chunk, write-back at each chunk boundary.  Bitwise-identical to
    # the in-HBM planned-packed trainer on tables that fit (tested).
    host_table: bool = False
    # device hot-row cache capacity in rows (0 = the chunk's worst-case
    # working set, capped at the table)
    hot_rows: int = 0
    # planned steps per host chunk (one device dispatch each)
    host_chunk_steps: int = 8
    # overlap upcoming chunks' master-row gathers with the current
    # chunk's device work: an evicted-and-retouched row may be read up
    # to prefetch_depth+1 = 3 chunks stale (the prefetcher runs that
    # far ahead of the write-back; bounded-staleness trade — the
    # default synchronous gather keeps the bitwise contract)
    host_gather_ahead: bool = False
    # persistent XLA compilation cache (hyperspace_tpu/compile_cache.py,
    # docs/observability.md "Compilation cache"): default ON at
    # <repo>/.cache/jax_compile (HYPERSPACE_COMPILE_CACHE env overrides);
    # a path points it elsewhere, 0 disables.  Run #2 of the same
    # program shapes deserializes executables instead of re-invoking XLA
    # (`jax/compile_cache_hit` counts them).
    compile_cache_dir: str | None = None
    # --- telemetry (docs/observability.md) -----------------------------
    # telemetry=1: run manifest as the FIRST JSONL record, span/* host
    # timings + ctr/* counter snapshots in every log record, and a final
    # telemetry_summary record.  Off (default) adds no per-step host
    # sync and no extra dispatches.
    telemetry: bool = False
    # write a Chrome/Perfetto trace_events JSON of the host spans here
    # (implies span recording even without telemetry=1)
    trace_out: str | None = None
    # write the counter registry as a Prometheus text-format snapshot
    # here every metrics_every= seconds (telemetry/exposition.py,
    # docs/observability.md "Live metrics"): atomic write-then-rename,
    # so a node exporter's textfile collector makes the training job
    # scrapeable with no port open.  Off (default) constructs nothing.
    metrics_out: str | None = None
    metrics_every: float = 30.0
    # >0: per-step phase decomposition for the first N dispatches
    # (train/telemetry.py): block_until_ready at the phase boundary so
    # data_wait / host_gather / device_step / write_back histograms
    # read real durations (the sync costs pipelining — bounded to the
    # profile window), plus jax.profiler trace annotations and the
    # compile-event hook.  0 (default) = free-running.
    profile_steps: int = 0
    # >0: sample the on-device numerical-health stats every N chunks
    # (telemetry/health.py): ball boundary margin, hyperboloid
    # constraint residual, nonfinite counts — logged as health/* records
    # and threshold-checked (warn; health_abort=1 raises instead)
    health_every: int = 0
    health_eps: float = 1e-2  # warn when boundary margin drops below
    health_tol: float = 1e-3  # warn when constraint violation exceeds
    health_abort: bool = False
    # --- resilience (docs/resilience.md) -------------------------------
    # chaos=site:kind[:key=value...][,...] arms the seeded fault
    # registry (resilience/faults.py) — e.g.
    # chaos=train.step_nan:nan:after=2 poisons one chunk; chaos_seed=
    # seeds probabilistic specs.  Off (default) every site is one
    # module-bool read.
    chaos: str | None = None
    chaos_seed: int = 0
    # rollback=N: divergence guard — on non-finite loss or a health
    # violation, rewind to the last COMMITTED checkpoint (needs
    # ckpt_dir=), re-seed stream-fed data past the poisoned chunk, and
    # record the incident; after N rollbacks the run fails loudly.
    # 0 (default) keeps warn/abort.  lr_backoff^attempt is computed,
    # recorded, and handed to the on_rollback hook — steppers that can
    # rebuild their optimizer apply it there (the built-in runners
    # currently re-seed only; docs/resilience.md).
    rollback: int = 0
    rollback_lr_backoff: float = 0.5
    coordinator: str = "127.0.0.1:9357"
    num_processes: int = 1
    process_id: int = 0


def _coerce(old: Any, s: str) -> Any:
    if old is None:
        return s
    t = type(old)
    if t is bool:
        return s.lower() in ("1", "true", "yes")
    if dataclasses.is_dataclass(old):
        raise ValueError("cannot override nested config directly")
    if t is tuple:
        return tuple(json.loads(s))
    try:
        return t(s)
    except (TypeError, ValueError):
        return s


def apply_overrides(cfg, overrides: dict[str, str]):
    """Apply {field: str} overrides to a (frozen) dataclass, coercing types."""
    updates = {}
    names = {f.name: f for f in dataclasses.fields(cfg)}
    for k, v in overrides.items():
        if k not in names:
            raise SystemExit(
                f"unknown option {k!r} for {type(cfg).__name__}; "
                f"known: {sorted(names)}")
        updates[k] = _coerce(getattr(cfg, k), v)
    return dataclasses.replace(cfg, **updates)


def split_overrides(pairs: list[str], run: RunConfig):
    """Partition key=value args into (run-config updates, workload updates)."""
    run_names = {f.name for f in dataclasses.fields(RunConfig)}
    run_kv, wl_kv = {}, {}
    for p in pairs:
        if "=" not in p:
            raise SystemExit(f"expected key=value, got {p!r}")
        k, v = p.split("=", 1)
        (run_kv if k in run_names else wl_kv)[k] = v
    return apply_overrides(run, run_kv), wl_kv


# --- workload runners ---------------------------------------------------------


def _maybe_accum(run: RunConfig, opt, state):
    """Wrap ``opt`` for gradient accumulation when ``run.accum > 1``.

    Rebuilds the optimizer state (a wrapped transform has a different
    state pytree — the old one must never be reused)."""
    if run.accum <= 1:
        return opt, state
    from hyperspace_tpu.optim.accum import with_grad_accumulation

    opt, opt_state = with_grad_accumulation(opt, state.params, run.accum)
    return opt, state._replace(opt_state=opt_state)


def _reject_accum(run: RunConfig, workload: str):
    if run.accum > 1:
        raise SystemExit(
            f"accum>1 is wired for hybonet/hvae only — the {workload} "
            "step updates full-batch (hgcn full-graph) or sparse rows "
            "(embeddings), where microbatch accumulation has no meaning")


def _graph_cache(run: RunConfig):
    """RunConfig.graph_cache → the data.graphs ``cache`` argument."""
    v = run.graph_cache.lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    if v == "auto":
        return "auto"
    raise SystemExit(f"graph_cache={run.graph_cache!r}: want auto/true/false")


def _chunk_run(run: RunConfig) -> RunConfig:
    """Round the step budget up to a scan_chunk multiple — every dispatch
    runs exactly one full chunk, so checkpoint/log step numbers always
    equal the steps actually taken."""
    from hyperspace_tpu.train import loop

    rounded = loop.round_steps_to_chunk(run.steps, run.scan_chunk)
    if rounded != run.steps:
        print(f"scan_chunk={run.scan_chunk}: step budget rounded up "
              f"{run.steps} -> {rounded} (every dispatch runs a full "
              "chunk)", flush=True)
    return dataclasses.replace(run, steps=rounded)


def _chunked(run: RunConfig, step_fn):
    """(stepper, steps_per_call): ``step_fn`` wrapped for chunked
    dispatch when ``run.scan_chunk > 1`` (one lax.scan program per
    ``scan_chunk`` steps, state donated), unchanged otherwise.  The
    run's precision policy rides into the chunk program (its arg-cast
    hook is a no-op for the CLI's closure-style steppers, but keeps the
    contract uniform for library callers — train/loop.py)."""
    k = max(int(run.scan_chunk), 1)
    if k <= 1:
        return step_fn, 1
    from hyperspace_tpu.train import loop

    return loop.make_chunked_stepper(step_fn, k, policy=run.precision), k


def _precision_default(run: RunConfig, overrides: dict) -> dict:
    """Copy the run-level ``precision=`` into the workload config unless
    the workload override set it explicitly (explicit wins)."""
    overrides.setdefault("precision", run.precision)
    return overrides


def run_poincare(run: RunConfig, overrides: dict):
    _reject_accum(run, "poincare")
    from hyperspace_tpu.data import wordnet
    from hyperspace_tpu.models import poincare_embed as pe

    if run.data_root:
        ds = wordnet.load_closure_tsv(run.data_root)
    else:
        ds = wordnet.synthetic_tree(depth=5, branching=4)
    cfg = apply_overrides(
        pe.PoincareEmbedConfig(num_nodes=ds.num_nodes),
        _precision_default(run, overrides))
    state, opt = pe.init_state(cfg, run.seed)
    pairs = jnp.asarray(ds.pairs)
    from hyperspace_tpu.manifolds import PoincareBall

    ball = PoincareBall(cfg.c)
    project = lambda st: st._replace(table=ball.proj(st.table))
    if run.host_table:
        # beyond-HBM path (train/host_embed.py): host master + device
        # hot-row cache, one planned-sparse dispatch per chunk
        from hyperspace_tpu.train import host_embed as he

        if cfg.sparse or run.scan_chunk > 1:
            raise SystemExit(
                "host_table=1 IS the planned-sparse chunked path — drop "
                "sparse=true / scan_chunk (chunking is host_chunk_steps=)")
        trainer = he.HostPlannedTrainer.from_state(
            cfg, opt, state, chunk_steps=run.host_chunk_steps,
            hot_rows=run.hot_rows, seed=run.seed,
            gather_ahead=run.host_gather_ahead,
            profile=bool(getattr(run, "profile_steps", 0)))
        trainer.run(ds.pairs, run.steps)
        if run.ckpt_dir:
            from hyperspace_tpu.parallel import multihost as mh

            d = os.path.join(run.ckpt_dir, "host_table")
            if jax.process_count() > 1:
                # pod save: each process writes ONLY its owned row range,
                # process 0 commits the manifest behind a barrier — same
                # on-disk layout, restorable at any process count
                # (parallel/host_table.save_owned_rows)
                from hyperspace_tpu.parallel import host_table as HT

                HT.save_owned_rows(trainer.master, d,
                                   barrier=lambda: mh.sync("host_table"))
            else:
                # sharded master save: one bounded block per shard, never
                # the full table in one array (parallel/host_table.py)
                trainer.master.save_sharded(d)
        if cfg.num_nodes > he.EVAL_MAX_ROWS:
            # materializing the table for eval would defeat the
            # beyond-HBM design at exactly the scale it exists for —
            # the sharded master (+ the serve lanes) is the product
            return {"workload": "poincare", "steps": int(trainer.step),
                    "host_table": True, "eval_skipped": "beyond-hbm"}
        state = project(trainer.to_state())
        with _eval_span():
            res = pe.evaluate(state.table, ds.pairs, cfg.c)
        return {"workload": "poincare", "steps": int(state.step),
                "host_table": True, **res}
    if run.scan_chunk > 1 and cfg.sparse:
        raise SystemExit(
            "scan_chunk>1 scans the dense step body only — drop "
            "sparse=true or scan_chunk (the planned-sparse scan lives "
            "in poincare_embed.train_epoch_planned_packed)")
    if run.scan_chunk > 1:
        run = _chunk_run(run)
    step_fn = pe.make_train_step(cfg)
    stepper, spc = _chunked(run, lambda st: step_fn(cfg, opt, st, pairs))
    health_fn = _maybe_health(run, lambda: _make_health(
        ball, params_of=lambda st: st.table))
    state, _ = _train_loop(run, state, stepper, project=project,
                           steps_per_call=spc, health_fn=health_fn)
    with _eval_span():
        res = pe.evaluate(state.table, ds.pairs, cfg.c)
    # state.step is the authoritative count (survives resume/chunk
    # rounding — a resumed chunked run can legitimately exceed run.steps)
    return {"workload": "poincare", "steps": int(state.step), **res}


def _resume_chunk(run: RunConfig, chunk_steps: int) -> int:
    """Starting chunk index for a SampledBatchStream — ceil(R/cs), see
    :func:`hyperspace_tpu.train.loop.resume_chunk` (the ONE home of the
    ceil-not-floor rationale, ADVICE r04)."""
    from hyperspace_tpu.train import loop

    return loop.resume_chunk(run.ckpt_dir, run.resume, chunk_steps)


def _sampled_chunk_steps(run: RunConfig, plan_steps: int) -> int:
    """Stream chunk size for the sampled trainers: ``plan_steps`` caps
    the device-resident pyramid footprint, the step budget caps it from
    above; with chunked dispatch the scan must divide the stream chunk so
    every pull lands on a chunk boundary."""
    cs = min(run.steps, plan_steps)
    if run.scan_chunk > 1 and (run.scan_chunk > cs or cs % run.scan_chunk):
        # never silently exceed the plan_steps footprint cap: a scan
        # bigger than the stream chunk would force bigger host batches
        # onto the device, which is exactly what plan_steps bounds
        raise SystemExit(
            f"scan_chunk={run.scan_chunk} must divide the sampled "
            f"stream's chunk size {cs} (= min(steps, plan_steps)) — "
            "raise plan_steps to a multiple of scan_chunk or lower "
            "scan_chunk")
    return cs


def _stream_stepper(stream, step_fn, steps_per_call: int = 1):
    """Stepper that pulls a fresh pyramid chunk every ``chunk_steps``
    DEVICE steps from a :class:`hgcn_sampled.SampledBatchStream` — long
    runs never recycle batches (VERDICT r3 #5).  ``step_fn(state,
    batches)`` may itself run ``steps_per_call`` steps per call (the
    chunked-dispatch wrapper); the caller guarantees ``chunk_steps %
    steps_per_call == 0`` so pulls stay on stream-chunk boundaries.  The
    device step indexes its pyramid row by ``state.step % chunk_steps``;
    a resume offset only rotates the within-chunk consumption order
    (batches are iid draws), every row of every chunk is still consumed
    exactly once.  The CHUNK sequence itself continues across restarts
    via ``_resume_chunk``."""
    holder = {"batches": None, "done": 0}

    def stepper(st):
        if holder["done"] % stream.chunk_steps == 0:
            holder["batches"] = stream.next()
        holder["done"] += steps_per_call
        return step_fn(st, holder["batches"])

    def on_rollback(restored_step, attempt, lr_scale):
        # divergence rollback (docs/resilience.md): drop the resident
        # chunk and realign to a chunk boundary so the NEXT call pulls
        # a FRESH stream chunk — batches are iid draws, so the poisoned
        # chunk is skipped, never replayed (replaying it would diverge
        # identically)
        holder["batches"] = None
        holder["done"] = 0

    # picked up by run_loop via the runner (`on_rollback=` kwarg)
    stepper.on_rollback = on_rollback
    return stepper


def hgcn_mode_defaults(base, overrides: dict, sampled: bool):
    """Mode-aware HGCN defaults (VERDICT r3 #2).

    The full-graph lr=1e-2 is measured-bad for two modes
    (docs/benchmarks.md): sampled minibatch gradients oscillate at 1e-2
    (val acc 0.3–0.76 swings) and the attention arm collapses 2-of-3
    seeds to the degenerate logits-0 solution.  3e-3 reaches the plateau
    in both studies; attention additionally gets grad-norm clipping
    (the collapse is driven by early gradient spikes).  Explicit lr= /
    clip_norm= overrides always win.  NOTE: a run resumed from a
    checkpoint re-derives its lr from config, so a pre-r4 sampled /
    attention checkpoint resumes at the NEW default lr unless the old
    value is passed explicitly.
    """
    use_att = _coerce(False, overrides.get("use_att", "false"))
    if (sampled or use_att) and "lr" not in overrides:
        base = dataclasses.replace(base, lr=3e-3)
    if use_att and "clip_norm" not in overrides:
        base = dataclasses.replace(base, clip_norm=1.0)
    return base


def run_hgcn(run: RunConfig, overrides: dict):
    _reject_accum(run, "hgcn")
    if run.scan_chunk > 1:
        run = _chunk_run(run)
    gc = _graph_cache(run)
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    task = overrides.pop("task", "lp")
    dataset = overrides.pop("dataset", "cora")
    # reorder=true|bfs → BFS locality order; reorder=community → LPA
    # community order (best block density on community graphs)
    reorder = overrides.pop("reorder", "false").lower()
    # neighbor-sampled minibatch mode (task=nc or lp): fixed-fanout
    # pyramids from the native sampler; supervises `batch` seeds/step
    sampled = overrides.pop("sampled", "false").lower() in ("1", "true", "yes")
    fanouts = tuple(json.loads(overrides.pop("fanouts", "[10, 10]")))
    batch = int(overrides.pop("batch", "512"))
    # batches are pre-planned host-side and recycled modulo this count —
    # caps the [S, B, f1, f2] id pyramid's device footprint on long runs
    plan_steps = int(overrides.pop("plan_steps", "64"))
    edges, x, labels, ncls, source = G.load_graph(dataset, run.data_root)
    if reorder not in ("0", "false", "no", "1", "true", "yes", "bfs",
                       "community"):
        raise SystemExit(
            f"reorder={reorder!r}: want true/false, bfs, or community")
    if reorder in ("1", "true", "yes", "bfs", "community"):
        # locality relabeling: feeds the cluster-pair kernel
        edges, x, labels, _ = G.apply_locality_order(
            edges, x, labels,
            method="community" if reorder == "community" else "bfs",
            cache=gc)
    base = hgcn_mode_defaults(
        hgcn.HGCNConfig(feat_dim=x.shape[1],
                        num_classes=ncls if task == "nc" else 0),
        overrides, sampled)
    cfg = apply_overrides(base, _precision_default(run, overrides))
    num_nodes = x.shape[0]
    from hyperspace_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(run.multihost, tp=run.tp)
    if task == "lp":
        split = G.split_edges(
            edges, num_nodes, x, seed=run.seed,
            cluster_min_pair=G.cluster_min_pair_for(cfg.use_att),
            cache=gc)
        if sampled:
            # minibatch LP (models/hgcn_sampled.py): pyramids over the
            # four endpoint chunks; full-graph eval on the shared tree
            if run.multihost:
                raise SystemExit(
                    "sampled=true is single-process — drop multihost=true")
            from hyperspace_tpu.models import hgcn_sampled as HS

            scfg = HS.SampledConfig(base=cfg, fanouts=fanouts,
                                    batch_size=batch)
            model_s, opt, state = HS.init_sampled_lp(
                scfg, feat_dim=x.shape[1], seed=run.seed)
            xt = jnp.asarray(np.asarray(x, np.float32))
            chunk_steps = _sampled_chunk_steps(run, plan_steps)
            with HS.SampledBatchStream(
                    scfg, "lp", num_nodes=num_nodes,
                    train_pos=split.train_pos,
                    chunk_steps=chunk_steps, seed=run.seed,
                    start_chunk=_resume_chunk(run, chunk_steps)) as stream:
                chunk_fn, spc = _chunked(
                    run, lambda st, b: HS.train_step_sampled_lp(
                        model_s, opt, st, xt, stream.deg, b))
                stepper = _stream_stepper(stream, chunk_fn,
                                          steps_per_call=spc)
                state, loss = _train_loop(
                    run, state, stepper, steps_per_call=spc,
                    health_fn=_maybe_health(run, _make_health))
            full = hgcn.HGCNLinkPred(cfg)
            with _eval_span():
                res = {"loss": float(loss), **hgcn.evaluate_lp(
                    full, state.params, split, "test")}
            return {"workload": "hgcn", "task": "lp", "dataset": dataset,
                    "source": source, "sampled": True, **res}
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=run.seed)
        ga = hgcn._device_graph(split.graph)
        if mesh is not None:
            from hyperspace_tpu.parallel import multihost as mh

            # per-host data plane: every process computes the SAME padded
            # pair batch (round_up_pairs pads to a mesh multiple, so the
            # rows divide evenly), feeds only its own row range, and
            # distribute_batch assembles the global batch-sharded array —
            # host→device supervision traffic scales 1/n_processes
            # (single-process this is a plain sharded device_put)
            train_pos = mh.distribute_batch(
                jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh)), mesh)
            # default multi-chip path: node-sharded encoder — each device
            # owns N/ndev nodes and their incoming edges (mean AND
            # attention aggregation; the receiver partition keeps the
            # attention softmax shard-local)
            step, state, ga_s = hgcn.make_node_sharded_step_lp(
                model, opt, num_nodes, mesh, state, split)
            stepper, spc = _chunked(run, lambda st: step(st, ga_s, train_pos))
        else:
            train_pos = jnp.asarray(split.train_pos)
            stepper, spc = _chunked(
                run, lambda st: hgcn.train_step_lp(model, opt, num_nodes,
                                                   st, ga, train_pos))
        state, loss = _train_loop(run, state, stepper, steps_per_call=spc,
                                  health_fn=_maybe_health(run, _make_health))
        with _eval_span():
            res = {"loss": float(loss), **hgcn.evaluate_lp(
                model, state.params, split, "test", ga=ga)}
    else:
        tr, va, te = G.node_split_masks(num_nodes, seed=run.seed)
        g = G.prepare(edges, num_nodes, x, labels=labels, num_classes=ncls,
                      train_mask=tr, val_mask=va, test_mask=te,
                      cluster_min_pair=G.cluster_min_pair_for(cfg.use_att),
                      cache=gc)
        if sampled:
            # minibatch trainer (models/hgcn_sampled.py): single-device
            # dense-block steps (a local mesh is simply unused);
            # evaluation runs the FULL-GRAPH model on the sampled-trained
            # parameters (identical param tree)
            if run.multihost:
                raise SystemExit(
                    "sampled=true is single-process — drop multihost=true "
                    "(sampled minibatch DP is not wired yet)")
            from hyperspace_tpu.models import hgcn_sampled as HS

            scfg = HS.SampledConfig(base=cfg, fanouts=fanouts,
                                    batch_size=batch)
            model_s, opt, state = HS.init_sampled_nc(
                scfg, feat_dim=x.shape[1], seed=run.seed)
            xt = jnp.asarray(np.asarray(x, np.float32))
            chunk_steps = _sampled_chunk_steps(run, plan_steps)
            with HS.SampledBatchStream(
                    scfg, "nc", num_nodes=num_nodes, edges=edges,
                    labels=labels, train_mask=tr,
                    chunk_steps=chunk_steps, seed=run.seed,
                    start_chunk=_resume_chunk(run, chunk_steps)) as stream:
                chunk_fn, spc = _chunked(
                    run, lambda st, b: HS.train_step_sampled_nc(
                        model_s, opt, st, xt, stream.deg, b))
                stepper = _stream_stepper(stream, chunk_fn,
                                          steps_per_call=spc)
                state, loss = _train_loop(
                    run, state, stepper, steps_per_call=spc,
                    health_fn=_maybe_health(run, _make_health))
            full = hgcn.HGCNNodeClf(cfg)
            with _eval_span():
                res = {"loss": float(loss),
                       **hgcn.evaluate_nc(full, state.params, g)}
            return {"workload": "hgcn", "task": "nc", "dataset": dataset,
                    "source": source, "sampled": True, **res}
        model, opt, state = hgcn.init_nc(cfg, g, seed=run.seed)
        ga = hgcn._device_graph(g)
        lab = jnp.asarray(g.labels)
        mask = jnp.asarray(g.train_mask)
        if mesh is not None:
            step, state, ga_s, lab_s, mask_s = (
                hgcn.make_node_sharded_step_nc(model, opt, mesh, state, g))
            stepper, spc = _chunked(
                run, lambda st: step(st, ga_s, lab_s, mask_s))
        else:
            stepper, spc = _chunked(
                run, lambda st: hgcn.train_step_nc(model, opt, st, ga, lab,
                                                   mask))
        state, loss = _train_loop(run, state, stepper, steps_per_call=spc,
                                  health_fn=_maybe_health(run, _make_health))
        with _eval_span():
            res = {"loss": float(loss),
                   **hgcn.evaluate_nc(model, state.params, g, ga=ga)}
    return {"workload": "hgcn", "task": task, "dataset": dataset,
            "source": source, **res}


def run_hybonet(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import text as T
    from hyperspace_tpu.models import hybonet

    dataset = overrides.pop("dataset", "text")
    ds, source = T.load_text(dataset, run.data_root)
    tr, te = ds.split(0.8, seed=run.seed)
    cfg = apply_overrides(
        hybonet.HyboNetConfig(vocab_size=ds.vocab_size,
                              num_classes=ds.num_classes,
                              max_len=ds.tokens.shape[1]),
        _precision_default(run, overrides))
    model, opt, state = hybonet.init_model(cfg, seed=run.seed)
    opt, state = _maybe_accum(run, opt, state)
    toks, mask, labels = (jnp.asarray(tr.tokens), jnp.asarray(tr.mask),
                          jnp.asarray(tr.labels))
    from hyperspace_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(run.multihost)
    if run.scan_chunk > 1:
        run = _chunk_run(run)
    if mesh is not None:
        step, state, (toks, mask, labels) = hybonet.make_sharded_step(
            model, opt, mesh, state, toks, mask, labels)
        base = lambda st: step(st, toks, mask, labels)
    else:
        base = lambda st: hybonet.train_step_sampled(model, opt, st, toks,
                                                     mask, labels)
    stepper, spc = _chunked(run, base)
    state, loss = _train_loop(run, state, stepper, steps_per_call=spc,
                              health_fn=_maybe_health(run, _make_health))
    with _eval_span():
        res = hybonet.evaluate(model, state.params, te)
    return {"workload": "hybonet", "source": source, "loss": float(loss), **res}


def run_hvae(run: RunConfig, overrides: dict):
    from hyperspace_tpu.data import mnist as M
    from hyperspace_tpu.models import hvae

    ds, source = M.load_mnist(run.data_root)
    cfg = apply_overrides(hvae.HVAEConfig(image_size=ds.images.shape[1]),
                          _precision_default(run, overrides))
    model, opt, state = hvae.init_model(cfg, seed=run.seed)
    opt, state = _maybe_accum(run, opt, state)
    x_all = jnp.asarray(ds.images, cfg.dtype)
    metrics = {}
    from hyperspace_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(run.multihost)
    if run.scan_chunk > 1:
        run = _chunk_run(run)
    if mesh is not None:
        step, state, x_all = hvae.make_sharded_step(model, opt, mesh, state,
                                                    x_all)
        fn = lambda st: step(st, x_all)
    else:
        fn = lambda st: hvae.train_step_sampled(model, opt, st, x_all)

    chunk_fn, spc = _chunked(run, fn)

    def stepper(st):
        if spc == 1:
            st, loss, recon, kl = chunk_fn(st)
        else:  # scanned chunk: per-step aux stacked [spc]; keep the last
            st, (loss, recon, kl) = chunk_fn(st)
            recon, kl = recon[-1], kl[-1]
        metrics["rk"] = (recon, kl)  # device arrays; fetched once at the end
        return st, loss

    state, loss = _train_loop(run, state, stepper, steps_per_call=spc,
                              health_fn=_maybe_health(run, _make_health))
    recon, kl = (float(v) for v in metrics.get("rk", (jnp.nan,) * 2))
    loss = float(loss)
    x = jnp.asarray(ds.images[:256], cfg.dtype)
    with _eval_span():
        iwae = float(hvae.iwae_bound(model, state.params, x,
                                     jax.random.PRNGKey(1), k=16))
    return {"workload": "hvae", "source": source, "loss": loss, "recon": recon,
            "kl": kl, "iwae": iwae}


def run_product(run: RunConfig, overrides: dict):
    _reject_accum(run, "product")
    from hyperspace_tpu.data import wordnet
    from hyperspace_tpu.models import product_embed as pme
    from hyperspace_tpu.parallel.mesh import auto_mesh

    if run.data_root:
        ds = wordnet.load_closure_tsv(run.data_root)
    else:
        ds = wordnet.synthetic_tree(depth=5, branching=3)
    cfg = apply_overrides(
        pme.ProductEmbedConfig(num_nodes=ds.num_nodes),
        _precision_default(run, overrides))
    state, curv_opt = pme.init_state(cfg, run.seed)
    pairs = jnp.asarray(ds.pairs)
    mesh = auto_mesh(run.multihost)
    if run.scan_chunk > 1:
        run = _chunk_run(run)
    if mesh is not None:
        step = pme.make_sharded_step(cfg, curv_opt, mesh)
        base = lambda st: step(st, pairs)
    else:
        base = lambda st: pme.train_step(cfg, curv_opt, state=st, pairs=pairs)
    stepper, spc = _chunked(run, base)
    def project(st):
        m = pme.build_manifold(cfg, st.params.c_raw)
        return st._replace(params=st.params._replace(
            table=m.proj(st.params.table)))

    def product_health():
        # the product manifold is rebuilt from the LEARNED curvatures
        # each check, so health reflects the geometry as trained
        from hyperspace_tpu.telemetry.health import health_stats

        def fn(st):
            m = pme.build_manifold(cfg, st.params.c_raw)
            return health_stats(st.params.table, m)

        return jax.jit(fn)

    state, _ = _train_loop(run, state, stepper, project=project,
                           steps_per_call=spc,
                           health_fn=_maybe_health(run, product_health))
    with _eval_span():
        res = pme.evaluate(cfg, state.params, ds.pairs)
    return {"workload": "product", **res,
            "curvatures": pme.curvatures(cfg, state.params)}


WORKLOADS = {
    "poincare": run_poincare,
    "hgcn": run_hgcn,
    "hybonet": run_hybonet,
    "hvae": run_hvae,
    "product": run_product,
}


# --- helpers ------------------------------------------------------------------


def _train_loop(run: RunConfig, state, stepper, project=None,
                steps_per_call=1, health_fn=None):
    """The ONE step loop every workload runner goes through — moved to
    :func:`hyperspace_tpu.train.loop.run_loop` (checkpoint/resume, JSONL
    logging with boundary-crossing cadence, per-chunk loss accumulation,
    telemetry spine); this thin wrapper keeps the import lazy so
    ``--help`` never pays for orbax.  A stepper carrying an
    ``on_rollback`` hook (the stream steppers do) hands it to the
    divergence guard — docs/resilience.md."""
    from hyperspace_tpu.train.loop import run_loop

    return run_loop(run, state, stepper, project=project,
                    steps_per_call=steps_per_call, health_fn=health_fn,
                    on_rollback=getattr(stepper, "on_rollback", None))


def _maybe_health(run: RunConfig, build):
    """``build() -> jitted health fn`` only when sampling is on — the
    health program never compiles for runs that will not use it."""
    return build() if run.health_every > 0 else None


def _make_health(tags=None, params_of=None):
    from hyperspace_tpu.telemetry.health import make_health_fn

    return make_health_fn(tags, params_of=params_of)


def _eval_span():
    """Trace span around a runner's final evaluation (host timeline
    completeness: eval time is part of the run artifact)."""
    from hyperspace_tpu.telemetry.trace import span

    return span("eval")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hyperspace_tpu.cli.train",
        description="Train a hyperspace-tpu workload.")
    ap.add_argument("workload", choices=sorted(WORKLOADS))
    ap.add_argument("overrides", nargs="*",
                    help="key=value overrides (run- or workload-config)")
    ap.add_argument("--yaml", default=None,
                    help="YAML file of overrides (CLI wins on conflict)")
    args = ap.parse_args(argv)

    pairs = []
    if args.yaml:
        import yaml

        with open(args.yaml) as f:
            doc = yaml.safe_load(f) or {}
        pairs += [f"{k}={json.dumps(v) if isinstance(v, list) else v}"
                  for k, v in doc.items()]
    pairs += args.overrides

    run, wl_overrides = split_overrides(pairs, RunConfig())
    from hyperspace_tpu import compile_cache, precision as precision_mod

    try:
        precision_mod.get_policy(run.precision)
    except ValueError as e:  # a typo'd preset is a usage error
        raise SystemExit(str(e)) from None
    if run.metrics_out and run.metrics_every <= 0:
        raise SystemExit(
            f"metrics_every={run.metrics_every}: want a positive "
            "snapshot cadence in seconds")
    try:
        # BEFORE any workload compile: every executable this run builds
        # should land in (or come from) the persistent cache
        compile_cache.activate(run.compile_cache_dir)
    except ValueError as e:  # unusable cache dir is a usage error
        raise SystemExit(str(e)) from None
    if run.rollback > 0 and not run.ckpt_dir:
        raise SystemExit(
            "rollback=N needs ckpt_dir= — the divergence guard rewinds "
            "to the last COMMITTED checkpoint (docs/resilience.md)")
    from hyperspace_tpu.resilience import faults as _faults

    try:
        chaos_armed = _faults.install_chaos(run.chaos, run.chaos_seed)
    except ValueError as e:  # malformed chaos= grammar is a usage error
        raise SystemExit(str(e)) from None
    if run.multihost and run.num_processes > 1:
        # the ONE process-group entry point (parallel/multihost.py) —
        # shared with the loopback harness, so CLI pods and the 2-process
        # CPU drills form their groups identically
        from hyperspace_tpu.parallel import multihost as mh

        mh.initialize(run.coordinator, run.num_processes, run.process_id)
    from hyperspace_tpu.telemetry import cli_session

    # enabled BEFORE the workload runs (not inside run_loop) so host
    # graph prep / cache misses land in the spans and trace too; the
    # trace dumps in cli_session's finally — a crash (incl. health_abort)
    # still produces it, covering everything up to the failure point.
    # Load the JSON at https://ui.perfetto.dev (host-level spans; the
    # XLA-level complement is train/profiling.trace).
    try:
        with cli_session(run.telemetry, run.trace_out):
            result = WORKLOADS[args.workload](run, wl_overrides)
        if chaos_armed:
            result["chaos"] = _faults.stats()
    finally:
        if chaos_armed:
            # the registry is process-global: an in-process caller
            # (tests, benches) must never inherit this run's faults
            _faults.clear()
    print(json.dumps(_json_safe(result)))
    return 0


def _json_safe(x):
    """Non-finite floats → null and numpy scalars → Python, so every
    emitted line is strict JSON (loss is nan when a resumed run had
    nothing left to do or a run diverged; a NaN table row reaches the
    serve CLI's response stream the same way — all must print parseably).
    Shared by the train and serve CLIs."""
    import math

    if isinstance(x, np.generic):
        x = x.item()
    if isinstance(x, float) and not math.isfinite(x):
        return None
    if isinstance(x, dict):
        return {k: _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    return x


if __name__ == "__main__":
    sys.exit(main())
