"""Serving entry point: ``python -m hyperspace_tpu.cli.serve``.

Three modes, same ``key=value`` override grammar as the train CLI:

    # freeze the newest committed checkpoint step into a serving artifact
    python -m hyperspace_tpu.cli.serve export \
        ckpt=runs/poincare/ckpt out=runs/poincare/artifact \
        workload=poincare c=1.0

    # one-shot queries (tests, smoke checks): prints one JSON line
    python -m hyperspace_tpu.cli.serve query artifact=runs/poincare/artifact \
        ids=0,1,2 k=5
    python -m hyperspace_tpu.cli.serve query artifact=... u=0,1 v=2,3 prob=1

    # stdin/JSONL loop: one request per line, one JSON response per line
    python -m hyperspace_tpu.cli.serve serve artifact=... telemetry=1

    # asyncio HTTP front door with continuous batching (port=0 =
    # ephemeral; the bound port is announced on stderr)
    python -m hyperspace_tpu.cli.serve serve-http artifact=... \
        port=8080 max_wait_us=2000 queue_max=64 deadline_ms=50

    # shard the table across the chips (mesh=-1 = all local devices)
    python -m hyperspace_tpu.cli.serve serve artifact=... mesh=-1

Loop-mode requests:

    {"op": "topk",   "ids": [0, 1, 2], "k": 5}
    {"op": "score",  "u": [0, 1], "v": [2, 3], "prob": true}
    {"op": "upsert", "ids": [7, 120], "rows": [[...], [...]]}
    {"op": "delete", "ids": [3]}
    {"op": "stats"}

``upsert``/``delete`` need ``live=1`` (the artifact's engine is wrapped
in a :class:`~hyperspace_tpu.serve.delta.LiveQueryEngine`; ``delta_cap=``
/ ``compact_at=`` size the delta segment — docs/serving.md "Live index
and rollover"); against a frozen engine they answer a ``validation``
error.

Responses mirror the request (``neighbors``/``dists``, ``scores``, or
the counter snapshot); a failed line yields ``{"error": {"kind": ...,
"message": ...}}`` with a machine-readable kind (``parse`` /
``validation`` / ``deadline_exceeded`` / ``overloaded`` / ``internal``
— docs/serving.md "Error taxonomy") and the loop continues — a
malformed line must never take the server down, and no line is ever
silently dropped.  ``deadline_ms=``/``queue_max=`` arm per-request
deadlines and bounded-queue admission control with a degradation
ladder; SIGTERM drains gracefully (docs/resilience.md).
Telemetry wiring matches the train CLI: ``telemetry=1`` installs the
recompile hook and prints a closing summary line to stderr,
``trace_out=`` dumps the host spans (each batch runs under a ``query``
span) as Chrome ``trace_events`` JSON in a ``finally``.  The serve loop
additionally prints a one-line ``serve/e2e_ms`` latency summary (count,
p50/p95/p99 — docs/observability.md "Histograms") to stderr on exit and
alongside every ``stats`` response.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import io
import json
import os
import sys

import numpy as np

from hyperspace_tpu.cli.train import _json_safe, apply_overrides


@dataclasses.dataclass
class ServeConfig:
    # shared
    artifact: str | None = None   # artifact dir (query/serve)
    telemetry: bool = False
    trace_out: str | None = None
    # export
    ckpt: str | None = None       # CheckpointManager dir
    out: str | None = None        # artifact dir to write
    workload: str = "poincare"    # poincare | lorentz | product
    # curvature the run TRAINED with — required for poincare/lorentz
    # export (not recoverable from the checkpoint; no silent default)
    c: str | None = None
    factors: str = ""             # product factor layout JSON [[kind, dim], ...]
    step: int = -1                # checkpoint step (-1 = newest committed)
    overwrite: bool = False
    # export: build an IVF index (hyperbolic k-means; serve/index.py)
    # into the artifact.  index=1 with ncells=0 picks ~sqrt(N) cells;
    # ncells=K alone also implies index=1.
    index: bool = False
    ncells: int = 0
    # export: also ship a packed scan lane (int4 | pq) in the artifact
    # (serve/artifact.py QuantPayload — pq freezes the trained
    # codebooks so every replica ranks through the same centers)
    quant: str = ""
    # query / serve
    k: int = 10
    ids: str = ""                 # comma-separated query ids (one-shot topk)
    u: str = ""                   # comma-separated endpoints (one-shot score)
    v: str = ""
    prob: bool = False            # score as Fermi–Dirac link probability
    fd_r: float = 2.0
    fd_t: float = 1.0
    min_bucket: int = 8
    max_bucket: int = 1024
    cache_size: int = 65536
    chunk_rows: int = 0           # 0 = auto from the tile budget
    # devices on the mesh's `model` axis to row-shard the table over:
    # 0 = single-device (no mesh), -1 = all local devices, N = first N.
    # A 1-device mesh runs the single-device program (bit-compatible).
    mesh: int = 0
    # two_stage | carry | fused (fused = the Pallas scan-top-k kernel,
    # rank-identical answers; docs/serving.md, docs/kernels.md)
    scan_mode: str = "two_stage"
    # table-scan precision: f32 (default, bit-identical) | bf16 (scan a
    # bf16 table copy, rescore candidates in f32 — docs/precision.md) |
    # int8 (per-row symmetric quantized scan copy at a quarter of the
    # table bytes, same f32 rescore — docs/serving.md "Quantized scan
    # lane") | int4 (two nibbles per byte + f16 scale, ~1/6 the bytes) |
    # pq (product-quantized codes + hyperbolic-aware codebooks, wider
    # over-fetch — docs/serving.md "Sub-int8 lanes"; an artifact
    # exported with a matching quant payload serves its shipped
    # codes/codebooks instead of re-packing)
    precision: str = "f32"
    # IVF probing (query/serve): cells probed per query.  0 = exact
    # scan; needs an artifact exported with an index.  nprobe >= ncells
    # or a sub-threshold table fall back to the exact program
    # (docs/serving.md "Approximate retrieval").
    nprobe: int = 0
    # --- live mutable index (serve/delta.py; docs/serving.md "Live
    # index and rollover") ----------------------------------------------
    # live=1 wraps the engine in a LiveQueryEngine: upsert/delete ops
    # (stdin loop) and POST /v1/upsert | /v1/delete (front door) mutate
    # through a delta segment with tombstone masking; frozen serving
    # (the default) rejects mutations with a validation error.
    # Incompatible with scan_mode=fused (no tombstone lane).
    live: bool = False
    # delta-segment capacity in rows (static shape — the merged query
    # path compiles once per bucket whatever the mutation rate)
    delta_cap: int = 1024
    # background-compaction trigger: occupancy fraction of delta_cap at
    # which a compaction thread folds the segment into a rebuilt base
    compact_at: float = 0.75
    # --- overload safety (docs/resilience.md) --------------------------
    # default per-request deadline in ms (0 = none); a request's own
    # "deadline_ms" field overrides.  Expired requests answer
    # error.kind=deadline_exceeded — never dispatched late, never
    # silently dropped.
    deadline_ms: float = 0.0
    # bounded admission queue: > N concurrent requests shed with
    # error.kind=overloaded, and queue pressure drives the degradation
    # ladder (nprobe steps toward 1, then cache-only).  0 = off.
    queue_max: int = 0
    # fault injection (resilience/faults.py), e.g.
    # chaos=serve.dispatch:latency:ms=50:times=3
    chaos: str | None = None
    chaos_seed: int = 0
    # --- HTTP front door (serve-http mode; docs/serving.md) ------------
    # bind address + port (0 = ephemeral; the bound port is announced
    # as "[serve-http] listening on HOST:PORT" on stderr)
    host: str = "127.0.0.1"
    port: int = 0
    # continuous-batching max wait: a pending bucket that has not
    # exactly filled a power-of-two rung flushes after this many µs
    max_wait_us: float = 2000.0
    # --- compile-time control (docs/serving.md "Warm starts") ----------
    # persistent XLA compilation cache (hyperspace_tpu/compile_cache.py):
    # default ON at <repo>/.cache/jax_compile (HYPERSPACE_COMPILE_CACHE
    # env overrides); a path points it elsewhere, 0 disables.  A serve
    # restart then deserializes its executables instead of re-compiling
    # the whole bucket ladder.
    compile_cache_dir: str | None = None
    # startup bucket prewarm: compile the configured bucket ladder
    # (× the IVF degradation-ladder widths) BEFORE serving traffic —
    # serve mode warms before reading stdin, serve-http before the
    # listeners open, so the first real request on every bucket is warm.
    # 0 (default) = off; 1 = warm k= (the config's k); a comma list
    # ("5,10") warms those k values.
    prewarm: str = "0"
    # --- observability plane (docs/observability.md "Live metrics,
    # access log, and the flight recorder") ----------------------------
    # serve-session JSONL (train-CLI record shapes): a run_manifest
    # first record and a closing telemetry_summary, so read_jsonl
    # tooling works on serve sessions too
    log: str | None = None
    # structured JSONL access log: one line per serve request —
    # request_id, route, buckets, collator flush id, queue-wait/
    # dispatch/e2e ms, cache hits, degrade level, taxonomy outcome
    access_log: str | None = None
    # rolling SLO window (telemetry/window.py): p50/p95/p99 + shed/
    # deadline/error rates over the last N seconds from histogram ring
    # deltas, surfaced in stats responses, /metrics, and the exit
    # summary.  0 disables.
    window_s: float = 60.0
    # latency-aware degradation signal: with queue_max>0 and a window,
    # a windowed e2e p99 past this many ms drives the ladder down even
    # without queue pressure.  0 (default) = queue-depth-only.
    slo_ms: float = 0.0
    # flight recorder (serve/access.py): keep a bounded ring of recent
    # access records and dump a timestamped incident JSONL here on
    # typed-error bursts, degrade transitions, and SIGTERM drain
    incident_dir: str | None = None
    # span-level pipeline tracing (telemetry/spans.py): every request
    # decomposes into queue_wait / collate_wait / dispatch /
    # device_compute / rescore / serialize stages — per-stage
    # histograms on /metrics, stage breakdowns in the access log, and
    # full span trees on failed/slow requests and incident dumps.
    # Adds a device sync per dispatch (docs/observability.md "Spans").
    trace: bool = False
    # slow-query JSONL: with slo_ms>0, a request past the SLO writes
    # its full access record + span tree here (implies trace=1)
    slow_log: str | None = None
    # --- multi-tenant serving (serve-http only; serve/registry.py,
    # docs/serving.md "Multi-tenant front door") -----------------------
    # tenant roster: a JSON list (inline, or a path to a .json file) of
    # {"name", "artifact", "weight"?, "queue_max"?, "deadline_ms"?,
    # "slo_ms"?, "precision"?, "nprobe"?} objects — each tenant gets
    # its own engine + batcher + degradation ladder + SLO window behind
    # the ONE front door; unlisted knobs inherit this config's values.
    # The FIRST tenant is the default route (requests without a
    # "tenant" field).  Mutually exclusive with artifact= and live=1.
    tenants: str | None = None
    # engine-paging budget in MiB of device table bytes (0 = unlimited):
    # past it, idle tenants' engines are dropped (the artifact stays
    # the host master) and rebuilt on demand, prewarmed off the hot path
    device_budget_mb: float = 0.0


def _ids(s: str, name: str) -> list[int]:
    try:
        out = [int(t) for t in s.split(",") if t.strip() != ""]
    except ValueError:
        raise SystemExit(f"{name}={s!r}: want comma-separated integers")
    if not out:
        raise SystemExit(f"{name}= is required (comma-separated ids)")
    return out


def _build(cfg: ServeConfig):
    """(engine, batcher) from the committed artifact."""
    from hyperspace_tpu.serve import (QueryEngine, RequestBatcher,
                                      load_artifact)

    if not cfg.artifact:
        raise SystemExit("artifact= is required for query/serve modes")
    mesh = None
    if cfg.mesh:
        from hyperspace_tpu.parallel.mesh import model_mesh

        try:
            mesh = model_mesh(cfg.mesh)
        except ValueError as e:
            raise SystemExit(f"mesh={cfg.mesh}: {e}") from None
    art = load_artifact(cfg.artifact)
    try:
        eng = QueryEngine.from_artifact(art, chunk_rows=cfg.chunk_rows,
                                        mesh=mesh, scan_mode=cfg.scan_mode,
                                        precision=cfg.precision,
                                        nprobe=cfg.nprobe)
        if cfg.live:
            # mutable serving: the artifact table becomes the host
            # master (a writable copy — the mmapped artifact stays
            # pristine) and the frozen engine becomes the base under a
            # delta segment (serve/delta.py)
            from hyperspace_tpu.parallel.host_table import HostEmbedTable
            from hyperspace_tpu.serve.delta import LiveQueryEngine

            master = HostEmbedTable.from_array(
                np.array(art.table, np.float32))
            eng = LiveQueryEngine(eng, master, capacity=cfg.delta_cap,
                                  compact_at=cfg.compact_at)
    except ValueError as e:  # bad scan_mode/chunk_rows/precision/nprobe
        raise SystemExit(str(e)) from None
    # --- observability plane (ServeConfig docstrings): window, access
    # log, flight recorder — all optional, wired into the batcher so
    # every serving surface (stdin loop, one-shot query, front door)
    # carries the same records
    window = recorder = alog = sink = slow = slow_sink = None
    if cfg.window_s < 0:
        raise SystemExit(f"window_s must be >= 0; got {cfg.window_s}")
    if cfg.window_s:
        from hyperspace_tpu.telemetry.window import SloWindow

        window = SloWindow(cfg.window_s)
    if cfg.trace or cfg.slow_log:
        # slow_log= needs span trees to attach, so it implies trace=
        from hyperspace_tpu.telemetry import spans

        spans.enable()
    try:
        if cfg.incident_dir:
            from hyperspace_tpu.serve.access import FlightRecorder

            recorder = FlightRecorder(cfg.incident_dir)
        if cfg.access_log or recorder is not None:
            from hyperspace_tpu.serve.access import AccessLog

            alog = AccessLog(cfg.access_log, recorder=recorder)
            sink = alog.emit
        if cfg.slow_log:
            from hyperspace_tpu.serve.access import AccessLog

            slow = AccessLog(cfg.slow_log)
            slow_sink = slow.emit
    except OSError as e:  # uncreatable/unwritable path is a usage error
        raise SystemExit(f"observability path: {e}") from None
    try:
        batcher = RequestBatcher(eng, min_bucket=cfg.min_bucket,
                                 max_bucket=cfg.max_bucket,
                                 cache_size=cfg.cache_size,
                                 queue_max=cfg.queue_max,
                                 deadline_ms=cfg.deadline_ms,
                                 window=window, slo_ms=cfg.slo_ms,
                                 access_sink=sink, recorder=recorder,
                                 slow_sink=slow_sink)
    except ValueError as e:  # bad queue_max/deadline_ms/slo_ms
        raise SystemExit(str(e)) from None
    batcher.access_log = alog  # closed by the serve-session bracket
    batcher.slow_log = slow
    return eng, batcher


def _build_registry(cfg: ServeConfig, prewarm_ks: list[int]):
    """The serve-http multi-tenant path: ``tenants=`` (inline JSON or a
    path to a JSON file) → a fully-built
    :class:`~hyperspace_tpu.serve.registry.EngineRegistry`.  Per-tenant
    fields override the shared config's serving knobs; malformed
    rosters are usage errors before any engine builds."""
    from hyperspace_tpu.serve.registry import EngineRegistry

    if cfg.artifact:
        raise SystemExit("tenants= and artifact= are mutually exclusive "
                         "(each tenant names its own artifact)")
    if cfg.live:
        raise SystemExit("tenants= does not support live=1 yet (the "
                         "delta segment is per-engine state that "
                         "engine paging would drop)")
    text = cfg.tenants
    if text and os.path.exists(text):
        try:
            with open(text, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            raise SystemExit(f"tenants={cfg.tenants}: {e}") from None
    try:
        roster = json.loads(text or "")
    except json.JSONDecodeError as e:
        raise SystemExit(
            f"tenants= wants a JSON list (inline or a file path): {e}"
        ) from None
    if (not isinstance(roster, list) or not roster
            or not all(isinstance(t, dict) for t in roster)):
        raise SystemExit(
            "tenants= wants a non-empty JSON list of tenant objects")
    reg = EngineRegistry(device_budget_mb=cfg.device_budget_mb,
                         max_wait_us=cfg.max_wait_us,
                         prewarm_ks=prewarm_ks)
    try:
        for t in roster:
            name, artifact = t.get("name"), t.get("artifact")
            if not (isinstance(name, str) and name
                    and isinstance(artifact, str) and artifact):
                raise SystemExit(
                    f"tenant entry {t!r}: wants string \"name\" and "
                    "\"artifact\" fields")
            unknown = set(t) - {"name", "artifact", "weight",
                                "queue_max", "deadline_ms", "slo_ms",
                                "precision", "nprobe"}
            if unknown:
                raise SystemExit(
                    f"tenant {name!r}: unknown field(s) "
                    f"{sorted(unknown)}")
            reg.add_tenant(
                name, artifact,
                weight=float(t.get("weight", 1.0)),
                window_s=cfg.window_s,
                engine_kw=dict(
                    chunk_rows=cfg.chunk_rows,
                    scan_mode=cfg.scan_mode,
                    precision=t.get("precision", cfg.precision),
                    nprobe=int(t.get("nprobe", cfg.nprobe))),
                batcher_kw=dict(
                    min_bucket=cfg.min_bucket,
                    max_bucket=cfg.max_bucket,
                    cache_size=cfg.cache_size,
                    queue_max=int(t.get("queue_max", cfg.queue_max)),
                    deadline_ms=float(t.get("deadline_ms",
                                            cfg.deadline_ms)),
                    slo_ms=float(t.get("slo_ms", cfg.slo_ms))))
    except (ValueError, TypeError, OSError) as e:
        # bad artifact / duplicate name / bad knob values: usage errors
        raise SystemExit(f"tenants=: {e}") from None
    return reg


def _prewarm_ks(cfg: ServeConfig) -> list[int]:
    """The ``prewarm=`` flag parsed into the k values to warm ([] = off;
    docstring on the ServeConfig field).  Malformed values are clean
    usage errors — a typo'd prewarm silently serving cold would defeat
    the flag's whole point."""
    v = cfg.prewarm.strip().lower()
    if v in ("", "0", "false", "no", "off"):
        return []
    if v in ("1", "true", "yes", "on"):
        return [cfg.k]
    try:
        ks = [int(t) for t in v.split(",") if t.strip()]
    except ValueError:
        raise SystemExit(
            f"prewarm={cfg.prewarm!r}: want 0/1 or a comma-separated "
            "list of k values to warm") from None
    if not ks or any(k < 1 for k in ks):
        raise SystemExit(
            f"prewarm={cfg.prewarm!r}: k values must be >= 1")
    return ks


def _run_prewarm(batcher, ks: list[int]) -> None:
    """Warm the ladder and announce it on stderr (diagnostics — stdout
    stays the response stream).  Invalid ks for this table (k past the
    row count) are usage errors, same class as a bad query k."""
    if not ks:
        return
    try:
        info = batcher.prewarm(ks)
    except ValueError as e:
        raise SystemExit(f"prewarm: {e}") from None
    try:
        print(f"[serve] prewarmed {info['programs']} program(s) over "
              f"buckets {info['buckets']} ks {info['ks']} in "
              f"{info['seconds']:.2f}s", file=sys.stderr, flush=True)
    except (OSError, ValueError):
        pass  # closed stderr: announcement loss only


def run_export(cfg: ServeConfig) -> dict:
    from hyperspace_tpu.serve import export_from_checkpoint

    if not (cfg.ckpt and cfg.out):
        raise SystemExit("export needs ckpt= and out=")
    model_config: dict = {}
    if cfg.workload in ("poincare", "lorentz"):
        if cfg.c is None:
            raise SystemExit(
                f"export workload={cfg.workload} requires c= (the "
                "curvature the run trained with — a wrong default would "
                "freeze the wrong metric into the artifact)")
        try:
            model_config["c"] = float(cfg.c)
        except ValueError:
            raise SystemExit(f"c={cfg.c!r}: want a float") from None
    elif cfg.factors:
        try:
            model_config["factors"] = json.loads(cfg.factors)
        except json.JSONDecodeError as e:
            raise SystemExit(
                f"factors={cfg.factors!r}: want JSON [[kind, dim], ...] "
                f"({e})") from None
    index_ncells = None
    if cfg.index or cfg.ncells:
        if cfg.ncells < 0:
            raise SystemExit(f"ncells={cfg.ncells}: want 0 (auto) or >= 2")
        index_ncells = cfg.ncells or -1  # <= 0 = auto (~sqrt(N))
    if cfg.quant and cfg.quant not in ("int4", "pq"):
        raise SystemExit(f"quant={cfg.quant!r}: want int4 or pq")
    try:
        art = export_from_checkpoint(
            cfg.ckpt, cfg.out, workload=cfg.workload,
            model_config=model_config,
            step=None if cfg.step < 0 else cfg.step,
            overwrite=cfg.overwrite, index_ncells=index_ncells,
            quant_lane=cfg.quant or None)
    except ValueError as e:  # bad ncells for the table size: usage
        raise SystemExit(str(e)) from None
    out = {"mode": "export", "out": cfg.out, "workload": cfg.workload,
           "num_nodes": art.num_nodes, "dim": art.dim, "step": art.step,
           "fingerprint": art.fingerprint}
    if art.index is not None:
        out["index"] = {"ncells": art.index.ncells,
                        "max_cell": art.index.max_cell,
                        "fingerprint": art.index.fingerprint}
    if art.quant is not None:
        out["quant"] = {"lane": art.quant.lane,
                        "fingerprint": art.quant.fingerprint}
    return out


def run_query(cfg: ServeConfig) -> dict:
    from hyperspace_tpu.serve.errors import ServeError

    _eng, batcher = _build(cfg)
    # request-shaped ValueErrors (k out of range, IVF probe capacity /
    # under-fill) and the typed serve errors (deadline/overload) are
    # usage errors in one-shot mode: clean exit, no traceback — the
    # serve loop answers the same errors per line
    try:
        if cfg.u or cfg.v:
            scores = batcher.score(_ids(cfg.u, "u"), _ids(cfg.v, "v"),
                                   prob=cfg.prob, fd_r=cfg.fd_r,
                                   fd_t=cfg.fd_t)
            return {"mode": "query", "scores": scores.tolist()}
        ids = _ids(cfg.ids, "ids")
        idx, dist = batcher.topk(ids, cfg.k)
    except (ValueError, ServeError) as e:
        raise SystemExit(str(e)) from None
    return {"mode": "query", "ids": ids, "k": cfg.k,
            "neighbors": idx.tolist(), "dists": dist.tolist()}


def _latency_line(baseline: dict | None = None) -> str:
    """One-line ``serve/e2e_ms`` summary (count + p50/p95/p99) from the
    latency histogram — printed to STDERR on serve-loop exit and per
    ``stats`` request (stdout stays strictly one response per line).
    With a ``baseline`` (a registry ``mark()`` from serve-loop start)
    the distribution is the delta over THIS session, not the process
    lifetime — an earlier in-process run's requests never inflate it."""
    from hyperspace_tpu.telemetry import registry as telem

    snap = telem.default_registry().snapshot(baseline=baseline)
    lat = snap.get("hist/serve/e2e_ms")
    if not lat or not lat.get("count"):
        return "[serve] latency e2e_ms: no requests"
    return ("[serve] latency e2e_ms count=%d p50=%.3f p95=%.3f p99=%.3f"
            % (lat["count"], lat["p50"], lat["p95"], lat["p99"]))


def _print_latency_stderr(baseline: dict | None = None) -> None:
    """Print the latency one-liner to stderr, OUTSIDE the request
    try-block and shielded: a consumer closing our stderr mid-serve
    (BrokenPipeError, or ValueError on a closed file) is a diagnostics
    loss, never a served-request failure or a loop exit."""
    try:
        print(_latency_line(baseline), file=sys.stderr, flush=True)
    except (OSError, ValueError):
        pass


def _window_line(batcher) -> str | None:
    """One-line rolling-window SLO summary (telemetry/window.py) — the
    'latency NOW' complement of the cumulative ``_latency_line``; None
    when no window is armed."""
    w = getattr(batcher, "window", None)
    if w is None:
        return None
    rep = w.report()
    e = rep.get("e2e_ms")
    if not e:
        return "[serve] window: no requests in the current window"
    return ("[serve] window %.1fs e2e_ms count=%d p50=%.3f p95=%.3f "
            "p99=%.3f qps=%.2f shed/s=%.2f err/s=%.2f"
            % (rep["window_s"], e["count"], e["p50"], e["p95"],
               e["p99"], rep["rate_qps"], rep["shed_rate"],
               rep["error_rate"]))


def _print_window_stderr(batcher) -> None:
    line = _window_line(batcher)
    if line is None:
        return
    try:
        print(line, file=sys.stderr, flush=True)
    except (OSError, ValueError):
        pass


@contextlib.contextmanager
def _serve_session(cfg: ServeConfig, batcher):
    """The serve modes' observability bracket: with ``log=``, write the
    train-CLI record shapes — a ``run_manifest`` FIRST record (the full
    ServeConfig as executed + device/backend identity) and a closing
    ``telemetry_summary`` scoped to this session by a registry mark —
    so ``read_jsonl`` tooling reads serve sessions exactly like train
    runs; always closes the access log on the way out.  Yields the
    session mark (the latency one-liners' baseline)."""
    from hyperspace_tpu.telemetry import registry as telem

    mark = telem.default_registry().mark()
    logger = None
    try:
        if cfg.log:
            from hyperspace_tpu.train.logging import MetricsLogger
            from hyperspace_tpu.train.loop import run_manifest

            try:
                logger = MetricsLogger(cfg.log, stdout=False)
            except OSError as e:
                # same usage-error mapping as access_log=/incident_dir=
                # (and the access log opened by _build still closes —
                # this raise unwinds through the finally below)
                raise SystemExit(f"log={cfg.log}: {e}") from None
            logger.event("run_manifest", **run_manifest(cfg))
        yield mark
    finally:
        if logger is not None:
            # summary must land even when the loop died — the session's
            # counters matter most in a post-mortem (train-loop rule)
            logger.event("telemetry_summary",
                         **telem.default_registry().snapshot(
                             "ctr/", baseline=mark))
            logger.close()
        alog = getattr(batcher, "access_log", None)
        if alog is not None:
            alog.close()
        slow = getattr(batcher, "slow_log", None)
        if slow is not None:
            slow.close()
        if cfg.trace or cfg.slow_log:
            # span enablement is process-global (_build turned it on):
            # an in-process caller (tests) must not inherit it
            from hyperspace_tpu.telemetry import spans

            spans.disable()


def _json_bool(req: dict, key: str, default: bool) -> bool:
    """Strict JSON boolean: the string \"false\" must be an error, not
    truthy — same reject-don't-coerce policy as the id/k validation."""
    v = req.get(key, default)
    if not isinstance(v, bool):
        raise ValueError(
            f"{key} must be a JSON boolean, got {type(v).__name__}")
    return v


def _req_deadline(req: dict):
    """Validate the optional per-request ``deadline_ms`` field (strict:
    a positive JSON number, not a bool/string) — None means "use the
    server's default"."""
    v = req.get("deadline_ms")
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0:
        raise ValueError(
            f"deadline_ms must be a positive number, got {v!r}")
    return float(v)


def _req_id(req: dict) -> str | None:
    """The optional per-request ``request_id`` (strict: a string) —
    the stdin loop's analog of the HTTP ``X-Request-Id`` header.  When
    present it is threaded into the lifecycle/access log AND echoed in
    the response line, so a client can join its requests to answers
    over the one shared stdout stream."""
    v = req.get("request_id")
    if v is None:
        return None
    if not isinstance(v, str) or not v:
        raise ValueError(
            f"request_id must be a non-empty string, got {v!r}")
    return v


def _handle(batcher, req: dict, entered=None) -> dict:
    """One request; ``entered`` (a 1-element list) is set True the
    moment a batcher entry is invoked — past that point the batcher
    owns the access log, before it the loop's error path must emit the
    record itself (the HTTP server's ``entered`` contract)."""
    op = req.get("op")
    rid = _req_id(req)
    echo = {} if rid is None else {"request_id": rid}
    if op == "topk":
        # k passes through raw: the batcher rejects non-integers rather
        # than truncating (a float k must be a client error, not k-1)
        ids, k = req["ids"], req.get("k", 10)
        exclude_self = _json_bool(req, "exclude_self", True)
        deadline_ms = _req_deadline(req)
        if entered is not None:
            entered[0] = True
        idx, dist = batcher.topk(ids, k, exclude_self=exclude_self,
                                 deadline_ms=deadline_ms, request_id=rid)
        return {"neighbors": idx.tolist(), "dists": dist.tolist(), **echo}
    if op == "score":
        u, v = req["u"], req["v"]
        prob = _json_bool(req, "prob", False)
        fd_r = float(req.get("fd_r", 2.0))
        fd_t = float(req.get("fd_t", 1.0))
        deadline_ms = _req_deadline(req)
        if entered is not None:
            entered[0] = True
        scores = batcher.score(u, v, prob=prob, fd_r=fd_r, fd_t=fd_t,
                               deadline_ms=deadline_ms, request_id=rid)
        return {"scores": scores.tolist(), **echo}
    if op == "upsert":
        ids, rows = req.get("ids"), req.get("rows")
        deadline_ms = _req_deadline(req)
        if entered is not None:
            entered[0] = True
        return {**batcher.upsert(ids, rows, deadline_ms=deadline_ms,
                                 request_id=rid), **echo}
    if op == "delete":
        deadline_ms = _req_deadline(req)
        if entered is not None:
            entered[0] = True
        return {**batcher.delete(req.get("ids"), deadline_ms=deadline_ms,
                                 request_id=rid), **echo}
    if op == "stats":
        # stats echoes too: a pipelined client must be able to join
        # EVERY answered line, scrape ops included
        return {**batcher.stats(), **echo}
    raise ValueError(
        f"unknown op {op!r} (want topk|score|upsert|delete|stats)")


def _loop_access(batcher, req, outcome: str) -> None:
    """Access-log a loop failure that never reached the batcher — the
    HTTP server's ``_serve_access`` analog for the stdin surface
    (parse errors, non-object lines, unknown ops, missing/malformed
    pre-dispatch fields).  The batcher emits for everything past its
    entry, so this covers exactly the complement: no double lines,
    and a malformed-line storm still feeds ``serve/errors``, the
    window's error rate, and the flight recorder's burst detector."""
    op = "none"
    rid = None
    if isinstance(req, dict):
        if isinstance(req.get("op"), str):
            op = req["op"]
        v = req.get("request_id")
        if isinstance(v, str) and v:
            rid = v
    batcher.emit_synthetic_access(op, request_id=rid, outcome=outcome)


def _echo_error_rid(resp: dict, req) -> dict:
    """Echo a well-formed ``request_id`` on ERROR responses too — a
    client pipelining requests over the one stdout stream must be able
    to join failures to requests, not only successes."""
    if isinstance(req, dict):
        rid = req.get("request_id")
        if isinstance(rid, str) and rid:
            return {**resp, "request_id": rid}
    return resp


def run_serve(cfg: ServeConfig, *, stdin=None, stdout=None) -> dict:
    """The JSONL loop; returns the closing stats dict (also printed to
    stderr when telemetry is on).  ``stdin``/``stdout`` injectable for
    tests.

    Error taxonomy (docs/serving.md): every failed line answers
    ``{"error": {"kind": ..., "message": ...}}`` with a machine-readable
    kind — ``parse`` (not JSON), ``validation`` (bad request),
    ``deadline_exceeded``, ``overloaded``, ``internal``.  Every read
    line gets exactly one response line; none is silently dropped.

    SIGTERM triggers **graceful drain**: stop admitting new lines,
    finish the in-flight request, print the drain notice + latency
    summary to stderr, and return the closing stats normally.  A real
    (fileno-backed) stdin is read through a select-polling raw reader
    (:func:`_poll_lines`) so an IDLE server drains within one poll
    interval too — a handler that only ran at the next protocol event
    would make a silent client block shutdown forever.  (From a
    non-main thread, where signal handlers cannot install, the loop
    simply runs without drain support; injected test streams without a
    fileno drain at line boundaries.)"""
    import signal
    import threading

    from hyperspace_tpu.serve.errors import ServeError, error_response
    from hyperspace_tpu.telemetry import registry as telem

    stdin = sys.stdin if stdin is None else stdin
    stdout = sys.stdout if stdout is None else stdout
    _eng, batcher = _build(cfg)
    # warm the ladder BEFORE the first line is read — the first real
    # request on every bucket must be warm (docs/serving.md "Warm
    # starts"); with the persistent cache on, warming a restarted
    # server is deserialization, not compilation
    _run_prewarm(batcher, _prewarm_ks(cfg))
    served = 0
    draining = threading.Event()
    prev_handler = None
    try:
        prev_handler = signal.signal(signal.SIGTERM,
                                     lambda _s, _f: draining.set())
    except ValueError:
        pass  # not the main thread: no drain hook, loop still serves
    # session bracket: log= parity records + access-log close; the
    # yielded mark is the latency one-liners' baseline (the
    # distribution of THIS serve loop, not the whole process)
    session = _serve_session(cfg, batcher)
    session_mark = session.__enter__()
    try:
        for line in _line_source(stdin, draining):
            if draining.is_set():
                break  # stop admitting; the prior request already flushed
            line = line.strip()
            if not line:
                continue
            is_stats = False
            req = None
            entered = [False]  # past a batcher entry, it owns the log
            try:
                try:
                    req = json.loads(line)
                except json.JSONDecodeError as e:
                    raise _ParseError(str(e)) from None
                if not isinstance(req, dict):
                    raise ValueError(
                        f"request must be a JSON object, "
                        f"got {type(req).__name__}")
                resp = _handle(batcher, req, entered)
                served += 1
                is_stats = req.get("op") == "stats"
            except _ParseError as e:
                resp = {"error": {"kind": "parse", "message": str(e)}}
                _loop_access(batcher, req, "parse")
            except (ServeError, ValueError, KeyError, TypeError,
                    OverflowError, OSError) as e:
                # OverflowError: numpy raises it for ints past the cast
                # width; belt-and-braces with the batcher's range check.
                # OSError: a per-request IO failure (incl. the injected
                # serve.dispatch ioerror chaos fault) answers
                # error.kind=internal and the loop keeps serving — one
                # request's IO trouble must not kill the server.
                # error_response maps ServeError kinds
                # (deadline_exceeded/overloaded), the stdlib validation
                # classes, and everything else (-> internal) onto the
                # taxonomy
                resp = error_response(e)
                if not entered[0]:
                    # the failure never reached the batcher: the loop
                    # must write the access record itself
                    _loop_access(batcher, req, resp["error"]["kind"])
            if "error" in resp:
                resp = _echo_error_rid(resp, req)
            print(json.dumps(_json_safe(resp)), file=stdout, flush=True)
            if is_stats:
                # the latency one-liner rides on stderr beside the stats
                # response — stdout stays one response per line
                _print_latency_stderr(session_mark)
                _print_window_stderr(batcher)
    finally:
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)
        if draining.is_set():
            try:
                print(f"[serve] drained: SIGTERM — stopped admitting, "
                      f"{served} request(s) served", file=sys.stderr,
                      flush=True)
            except (OSError, ValueError):
                pass  # diagnostics never sink the drain
            if batcher.recorder is not None:
                # SIGTERM is a flight-recorder trigger on the stdin
                # path too — shutdown leaves the same evidence the
                # front door's drain does (wait: the process exits next)
                batcher.recorder.dump("sigterm_drain", _cls="drain",
                                      wait=True)
        # the closing summary must survive an engine-level crash — the
        # accumulated distribution matters most in a post-mortem
        _print_latency_stderr(session_mark)
        _print_window_stderr(batcher)
        session.__exit__(None, None, None)
    return {"mode": "serve", "served": served,
            "drained": draining.is_set(), **batcher.stats()}


def run_serve_http(cfg: ServeConfig, *, ready=None) -> dict:
    """The asyncio HTTP front door (serve/server.py): concurrent
    ``POST /v1/topk`` / ``/v1/score`` / ``/v1/upsert`` /
    ``/v1/delete`` / ``/v1/stats`` + ``POST /admin/rollover`` + ``GET
    /healthz`` over the continuous-batching collator; SIGTERM drains
    exactly like the stdin loop (in-flight answered, new connections
    refused, latency summary on stderr).  ``ready(host, port)`` is
    called once the listener is bound — the default announces the port
    on stderr as a parseable ``[serve-http] listening on HOST:PORT``
    line (port=0 binds an ephemeral port).  ``/admin/rollover`` is
    armed with a builder that replays this config against the posted
    ``target`` artifact path (serve/rollover.py: the standby is built
    and prewarmed off-loop, the flip is health-gated and atomic)."""
    import asyncio

    from hyperspace_tpu.serve.server import run_front_door

    if cfg.max_wait_us < 0:  # usage error BEFORE the artifact load pays
        raise SystemExit(
            f"max_wait_us must be >= 0; got {cfg.max_wait_us}")
    prewarm_ks = _prewarm_ks(cfg)  # parse errors before the build pays

    def announce(host, port):
        try:
            print(f"[serve-http] listening on {host}:{port}",
                  file=sys.stderr, flush=True)
        except (OSError, ValueError):
            pass  # hyperlint: disable=swallow-base-exception — closed stderr: announcement loss only
        if ready is not None:
            ready(host, port)

    if cfg.tenants:
        # multi-tenant front door (serve/registry.py): one engine/
        # batcher/ladder stack per roster entry, weighted-fair dispatch
        # on the one shared executor, engine paging under the budget
        registry = _build_registry(cfg, prewarm_ks)
        with _serve_session(cfg, registry.default.batcher):
            try:
                result = asyncio.run(run_front_door(
                    registry=registry, host=cfg.host, port=cfg.port,
                    max_wait_us=cfg.max_wait_us, ready=announce,
                    prewarm_ks=prewarm_ks))
            except ValueError as e:  # prewarm k out of range
                raise SystemExit(f"prewarm: {e}") from None
            except OSError as e:
                raise SystemExit(
                    f"serve-http: cannot bind {cfg.host}:{cfg.port} "
                    f"— {e}") from None
        return {"mode": "serve_http", **result,
                "tenants": registry.stats()}
    _eng, batcher = _build(cfg)

    def rebuild(target: str):
        # SystemExit (how _build reports a bad artifact) would escape the
        # connection task uncaught — re-raise as the ValueError the front
        # door's error taxonomy maps to a 400 validation response.
        try:
            return _build(dataclasses.replace(cfg, artifact=target))[1]
        except SystemExit as e:
            raise ValueError(str(e)) from None

    with _serve_session(cfg, batcher):
        try:
            result = asyncio.run(run_front_door(
                batcher, host=cfg.host, port=cfg.port,
                max_wait_us=cfg.max_wait_us, ready=announce,
                prewarm_ks=prewarm_ks, rollover_builder=rebuild))
        except ValueError as e:  # prewarm k out of range for this table
            raise SystemExit(f"prewarm: {e}") from None
        except OSError as e:  # bind failure (port in use, bad host): usage
            raise SystemExit(
                f"serve-http: cannot bind {cfg.host}:{cfg.port} — {e}"
            ) from None
        _print_window_stderr(batcher)
    return {"mode": "serve_http", **result, **batcher.stats()}


class _ParseError(Exception):
    """Internal marker: the line was not JSON at all (kind=parse)."""


def _poll_lines(fd: int, draining):
    """Line iterator over a raw fd with a drain check every poll tick.

    A plain ``for line in sys.stdin`` blocks in ``readline`` — and
    PEP 475 retries the read after a signal handler runs, so a SIGTERM
    to an IDLE server would never drain until the client's next line.
    Reading the raw fd under a short ``select`` timeout bounds the
    drain latency at one tick; buffering by hand (rather than through
    the TextIO layer) avoids the classic select-vs-buffered-reader
    stall where a burst of lines sits unread in the text buffer while
    select waits on the drained fd."""
    import select

    buf = b""
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line, buf = buf[:nl + 1], buf[nl + 1:]
            yield line.decode("utf-8", errors="replace")
            continue
        if draining.is_set():
            return
        ready, _, _ = select.select([fd], [], [], 0.25)
        if not ready:
            continue
        chunk = os.read(fd, 65536)
        if not chunk:  # EOF; a trailing unterminated line still serves
            if buf:
                yield buf.decode("utf-8", errors="replace")
            return
        buf += chunk


def _line_source(stdin, draining):
    """The serve loop's line iterator: the polling raw-fd reader for
    real streams, plain iteration for injected test streams (StringIO
    and generators have no usable fileno — they drain at line
    boundaries instead)."""
    try:
        fd = stdin.fileno()
    except (AttributeError, OSError, ValueError, io.UnsupportedOperation):
        return iter(stdin)
    return _poll_lines(fd, draining)


MODES = {"export": run_export, "query": run_query, "serve": run_serve,
         "serve-http": run_serve_http}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hyperspace_tpu.cli.serve",
        description="Export serving artifacts and answer embedding queries.")
    ap.add_argument("mode", choices=sorted(MODES))
    ap.add_argument("overrides", nargs="*",
                    help="key=value overrides (ServeConfig fields)")
    args = ap.parse_args(argv)

    kv = {}
    for p in args.overrides:
        if "=" not in p:
            raise SystemExit(f"expected key=value, got {p!r}")
        k, v = p.split("=", 1)
        kv[k] = v
    cfg = apply_overrides(ServeConfig(), kv)

    from hyperspace_tpu import compile_cache
    from hyperspace_tpu.resilience import faults as _faults
    from hyperspace_tpu.telemetry import cli_session

    try:
        # BEFORE the engine builds: every bucket executable (and the
        # prewarm pass) should come from / land in the persistent cache
        compile_cache.activate(cfg.compile_cache_dir)
    except ValueError as e:  # unusable cache dir is a usage error
        raise SystemExit(str(e)) from None
    # the hook is unconditional here (idempotent, ~zero cost): the
    # serve stats' `recompiles` field is a CONTRACT number (flat once
    # warm) and must read honestly even with telemetry=0 and the
    # cache disabled — a counter that silently reads 0 would make
    # every cold start look warm
    from hyperspace_tpu.telemetry import registry as _telem_registry

    _telem_registry.install_jax_monitoring_hook()
    try:
        chaos_armed = _faults.install_chaos(cfg.chaos, cfg.chaos_seed)
    except ValueError as e:  # malformed chaos= grammar is a usage error
        raise SystemExit(str(e)) from None
    try:
        # stream=stderr: in serve mode stdout is the response stream
        with cli_session(cfg.telemetry, cfg.trace_out, stream=sys.stderr):
            result = MODES[args.mode](cfg)
        if chaos_armed:
            result["chaos"] = _faults.stats()
    finally:
        if chaos_armed:
            # process-global registry: an in-process caller (tests)
            # must never inherit this run's faults
            _faults.clear()
        if cfg.telemetry:
            from hyperspace_tpu.telemetry import registry as telem

            print(json.dumps({"telemetry_summary":
                              telem.snapshot("ctr/")}),
                  file=sys.stderr, flush=True)
    # serve mode's stdout is the response stream (one line per request,
    # strictly) and serve-http's responses ride the sockets; both
    # modes' closing stats are diagnostics and go to stderr
    print(json.dumps(_json_safe(result)),
          file=(sys.stderr if args.mode in ("serve", "serve-http")
                else sys.stdout))
    return 0


if __name__ == "__main__":
    sys.exit(main())
