"""Prometheus text exposition of the registry — the live scrape plane.

Everything the telemetry layer knows (PRs 2, 7) is post-hoc: counters
and histograms surface in JSONL records, bench artifacts, and stderr
summaries — but a *running* server exposes nothing a scraper can poll.
This module renders one :class:`~hyperspace_tpu.telemetry.registry.
Registry` in the Prometheus text format (v0.0.4), the lingua franca of
every scrape stack, so

- the HTTP front door serves it at ``GET /metrics``
  (``serve/server.py``), and
- a training run writes it periodically to a file
  (``metrics_out=``/``metrics_every=`` on the train CLI — a node
  exporter's textfile collector makes a training job scrapeable with
  no port open).

Format rules (pinned by the golden test in
``tests/telemetry/test_exposition.py``):

- **Names sanitize** as ``hyperspace_`` + the registry name with every
  non-``[a-zA-Z0-9_:]`` rune replaced by ``_`` — ``serve/e2e_ms`` →
  ``hyperspace_serve_e2e_ms``.  The ORIGINAL registry name rides the
  ``# HELP`` line, so a scrape maps back onto the catalog rows in
  docs/observability.md (``scripts/check_metrics_endpoint.py`` checks
  the round trip both directions).
- **Every sample carries** a ``process_index`` label (plus any caller
  extras), so multi-host scrapes merge instead of colliding.
- **Counters** render as ``counter``, **gauges** as ``gauge``,
  **histograms** as real Prometheus histograms: cumulative
  ``_bucket{le=...}`` lines + ``_sum`` + ``_count``.  The log-bucket
  scheme has ~283 finite edges; only edges where the cumulative count
  CHANGES are emitted (plus ``le="+Inf"``) — information-lossless
  (cumulative counts stay monotone and complete) and ~10 lines per
  live histogram instead of ~285.
- **Escaping**: HELP text escapes ``\\`` and newlines; label values
  escape ``\\``, ``\"``, and newlines.

:class:`MetricsFileWriter` is the train-side snapshotter: atomic
write-then-rename every ``every_s`` seconds, checked with one clock
read per call (``maybe_write`` sits on the chunk boundary — the
disabled default constructs nothing).
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional

from hyperspace_tpu.telemetry.histogram import HistogramSnapshot
from hyperspace_tpu.telemetry.registry import Registry, default_registry

PREFIX = "hyperspace_"
_BAD_RUNE_RX = re.compile(r"[^a-zA-Z0-9_:]")

# Per-tenant registry names embed the tenant as a suffix the exposition
# re-renders as a real Prometheus ``tenant`` label: the registry stays a
# flat name→value dict (no label machinery on the hot inc path), while a
# scrape sees one family per BASE name with tenant-labeled samples —
# ``serve/e2e_ms@tenant=en`` joins the ``serve/e2e_ms`` family as
# ``hyperspace_serve_e2e_ms{tenant="en",...}``.  The HELP line carries
# the base name, so the catalog round trip (check_metrics_endpoint.py ↔
# docs/observability.md) keys on ONE documented row per base metric.
TENANT_SEP = "@tenant="


def split_tenant(name: str) -> tuple:
    """``(base_name, tenant_or_None)`` for a registry metric name."""
    base, sep, tenant = name.partition(TENANT_SEP)
    return (base, tenant) if sep else (name, None)


def tenant_metric(name: str, tenant) -> str:
    """The per-tenant twin of registry metric ``name`` (see
    :data:`TENANT_SEP`); ``tenant=None`` returns the base name."""
    return f"{name}{TENANT_SEP}{tenant}" if tenant else name


def sanitize_name(name: str) -> str:
    """Registry name → Prometheus metric family name.

    ``serve/e2e_ms`` → ``hyperspace_serve_e2e_ms``; a leading digit
    after the prefix is fine (the prefix itself starts the name)."""
    return PREFIX + _BAD_RUNE_RX.sub("_", name)


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (text.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v) -> str:
    """Sample values: integers render bare (counters stay readable),
    floats via repr at full precision.  Non-finite values render as
    the format's ``NaN``/``+Inf``/``-Inf`` literals — one poisoned
    gauge (or an inf observation's histogram sum) must break that one
    sample's usefulness, never every future scrape."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _labels_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001  # hyperlint: disable=swallow-base-exception — jax absent/uninitialized: exposition must render anyway (label degrades to 0)
        return 0


def _hist_lines(san: str, labels: dict, snap: HistogramSnapshot) -> list:
    """Cumulative-bucket lines for one histogram snapshot.

    Bucket ``i`` (1-based within the finite range) spans
    ``[lo*g^(i-1), lo*g^i)``, so the cumulative count at
    ``le = lo*g^i`` includes the underflow bucket plus buckets
    ``1..i``.  Runs of edges where the cumulative count does not
    change are compressed to their LAST edge — the one immediately
    below the next populated bucket — so every emitted bucket keeps
    its true lower bound (PromQL's ``histogram_quantile`` interpolates
    linearly inside a bucket: dropping the lower-bound edge would
    stretch the bucket down to the previously emitted edge and pull
    quantile estimates far below the scheme's ~4.9 % error bound).
    Cumulative monotonicity and totals are preserved exactly; a live
    histogram emits ≤ 2 lines per populated run instead of ~285."""
    out = []

    def emit(i: int, c: int) -> None:
        edge = snap.lo * snap.growth ** i
        lab = dict(labels, le=f"{edge:.6g}")
        out.append(f"{san}_bucket{_labels_str(lab)} {c}")

    n = len(snap.counts) - 2
    cum = snap.counts[0]
    last_emitted = 0  # bucket-edge index of the last emitted line
    for i in range(1, n + 1):
        new_cum = cum + snap.counts[i]
        if new_cum != cum:
            if i - 1 >= 1 and last_emitted != i - 1:
                emit(i - 1, cum)  # the populated bucket's lower bound
            emit(i, new_cum)
            last_emitted = i
        cum = new_cum
    lab = dict(labels, le="+Inf")
    out.append(f"{san}_bucket{_labels_str(lab)} {snap.count}")
    out.append(f"{san}_sum{_labels_str(labels)} {_fmt(snap.sum)}")
    out.append(f"{san}_count{_labels_str(labels)} {snap.count}")
    return out


def render_prometheus(registry: Optional[Registry] = None,
                      labels: Optional[dict] = None) -> str:
    """The whole registry as Prometheus text (module docstring).

    ``labels`` are extra labels on every sample; ``process_index`` is
    always present (caller's value wins — a multi-host aggregator can
    re-stamp).  Families render in sorted registry-name order, so two
    scrapes of an idle process are byte-identical (the monotone-scrape
    check in ``check_metrics_endpoint.py`` depends on stable order
    only for readability — the parser is order-free)."""
    reg = default_registry() if registry is None else registry
    return render_export(*reg.export(), labels=labels)


def render_export(counters: dict, gauges: dict, hists: dict,
                  labels: Optional[dict] = None) -> str:
    """Render one raw ``Registry.export()`` tuple as Prometheus text —
    the registry-free half of :func:`render_prometheus`, so a multihost
    aggregator can render a MERGED export
    (``telemetry.aggregate.merge_exports``) through exactly the same
    format path a single process's scrape takes."""
    base = {"process_index": str(_process_index())}
    if labels:
        base.update({str(k): str(v) for k, v in labels.items()})
    lines: list[str] = []

    def _families(entries: dict) -> list:
        """[(base_name, [(labels, value), ...])] — tenant-suffixed names
        fold into their base family as tenant-labeled samples; within a
        family the unlabeled sample sorts first, tenants alphabetically
        (sorted() on the suffixed names gives exactly that order)."""
        fams: dict = {}
        for name in sorted(entries):
            bname, tenant = split_tenant(name)
            lab = dict(base, tenant=tenant) if tenant else base
            fams.setdefault(bname, []).append((lab, entries[name]))
        return sorted(fams.items())

    for name, samples in _families(counters):
        san = sanitize_name(name)
        lines.append(f"# HELP {san} {escape_help(name)}")
        lines.append(f"# TYPE {san} counter")
        for lab, v in samples:
            lines.append(f"{san}{_labels_str(lab)} {_fmt(v)}")
    for name, samples in _families(gauges):
        san = sanitize_name(name)
        lines.append(f"# HELP {san} {escape_help(name)}")
        lines.append(f"# TYPE {san} gauge")
        for lab, v in samples:
            lines.append(f"{san}{_labels_str(lab)} {_fmt(v)}")
    for name, samples in _families(hists):
        san = sanitize_name(name)
        lines.append(f"# HELP {san} {escape_help(name)}")
        lines.append(f"# TYPE {san} histogram")
        for lab, snap in samples:
            lines.extend(_hist_lines(san, lab, snap))
    return "\n".join(lines) + "\n"


class MetricsFileWriter:
    """Periodic exposition-to-file snapshotter (``metrics_out=``).

    ``maybe_write()`` costs one ``time.monotonic`` read until the
    cadence expires, then renders and writes ATOMICALLY (temp file +
    rename in the target directory) — a scraper's textfile collector
    never reads a torn snapshot.  ``write()`` forces one (run end —
    the final counters must land whatever the cadence)."""

    def __init__(self, path: str, every_s: float = 30.0, *,
                 registry: Optional[Registry] = None,
                 labels: Optional[dict] = None):
        if every_s <= 0:
            raise ValueError(f"metrics_every must be > 0; got {every_s}")
        self.path = path
        self.every_s = float(every_s)
        self._registry = registry
        self._labels = labels
        self.writes = 0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._next = time.monotonic()  # first maybe_write() emits

    def maybe_write(self) -> bool:
        if time.monotonic() < self._next:
            return False
        self.write()
        return True

    def write(self) -> None:
        self._next = time.monotonic() + self.every_s
        text = render_prometheus(self._registry, labels=self._labels)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, self.path)
        self.writes += 1
