"""Run-wide telemetry: trace spans, counter registry, numerical health.

The observability layer the chunked-dispatch loop (PR 1) made necessary:
K steps vanish into one ``lax.scan`` dispatch, prefetch and prep-cache
activity happens on background threads, and the only run artifact is a
JSONL of losses.  This package adds, with zero per-step host sync and
~zero cost when disabled (the default):

- :mod:`trace` — nested host wall-clock spans (``span("dispatch")``)
  aggregated into ``span/*`` JSONL fields per log boundary, plus a
  Chrome/Perfetto ``trace_events`` dump (``trace_out=`` on the CLI);
- :mod:`registry` — process-wide named counters/gauges (prep-cache
  hit/miss, prefetch stalls/queue depth, dispatches, recompiles via
  ``jax.monitoring``, checkpoint saves/seconds/bytes), snapshotted as
  ``ctr/*`` into every log record and a final ``telemetry_summary``;
- :mod:`histogram` — streaming latency histograms (``observe(name,
  ms)``: fixed log buckets, ~5% quantile error, mergeable snapshots),
  surfaced as ``hist/*`` entries (count/sum/min/max/p50..p99) in the
  same snapshots — the p50/p95/p99 layer the serve SLOs stand on;
- :mod:`health` — on-device hyperbolic numerical-health stats (ball
  boundary margin, hyperboloid constraint residual, nonfinite counts),
  sampled every ``health_every=`` chunks and threshold-checked.

Catalog + reading guide: docs/observability.md.
"""

import contextlib

from hyperspace_tpu.telemetry.health import (  # noqa: F401
    HealthMonitor,
    health_stats,
    make_health_fn,
)


@contextlib.contextmanager
def cli_session(telemetry: bool, trace_out, *, stream=None):
    """The CLI entry points' shared telemetry bracket (train and serve).

    Enables span recording + the jax recompile hook up front (BEFORE the
    workload, so host prep lands in the trace), and in a ``finally``
    dumps the Chrome trace — a crashed run must still produce its trace,
    and an OSError from the dump must never mask the exception this
    block may be unwinding — then disables recording.  ``stream`` is
    where the dump notices print (train: stdout, serve: stderr — serve's
    stdout is a strict response stream)."""
    if telemetry or trace_out:
        from hyperspace_tpu.telemetry import registry as _registry
        from hyperspace_tpu.telemetry import trace as _trace

        _trace.enable(keep_events=bool(trace_out))
        _registry.install_jax_monitoring_hook()
    try:
        yield
    finally:
        if trace_out:
            from hyperspace_tpu.telemetry.trace import default_tracer

            try:
                n = default_tracer().dump_chrome_trace(trace_out)
                print(f"[telemetry] {n} trace events -> {trace_out}",
                      file=stream, flush=True)
            except OSError as e:
                print(f"[telemetry] trace dump failed: {e!r}",
                      file=stream, flush=True)
        if telemetry or trace_out:
            from hyperspace_tpu.telemetry import trace as _trace

            _trace.disable()
from hyperspace_tpu.telemetry.exposition import (  # noqa: F401
    MetricsFileWriter,
    render_prometheus,
    sanitize_name,
)
from hyperspace_tpu.telemetry.histogram import (  # noqa: F401
    Histogram,
    HistogramSnapshot,
)
from hyperspace_tpu.telemetry.window import SloWindow  # noqa: F401
from hyperspace_tpu.telemetry.registry import (  # noqa: F401
    Registry,
    default_registry,
    install_jax_monitoring_hook,
    observe,
)
from hyperspace_tpu.telemetry.trace import (  # noqa: F401
    Tracer,
    default_tracer,
    span,
)
