"""Run-wide telemetry: trace spans, counter registry, numerical health.

The observability layer the chunked-dispatch loop (PR 1) made necessary:
K steps vanish into one ``lax.scan`` dispatch, prefetch and prep-cache
activity happens on background threads, and the only run artifact is a
JSONL of losses.  This package adds, with zero per-step host sync and
~zero cost when disabled (the default):

- :mod:`trace` — nested host wall-clock spans (``span("dispatch")``)
  aggregated into ``span/*`` JSONL fields per log boundary, plus a
  Chrome/Perfetto ``trace_events`` dump (``trace_out=`` on the CLI);
- :mod:`registry` — process-wide named counters/gauges (prep-cache
  hit/miss, prefetch stalls/queue depth, dispatches, recompiles via
  ``jax.monitoring``, checkpoint saves/seconds/bytes), snapshotted as
  ``ctr/*`` into every log record and a final ``telemetry_summary``;
- :mod:`health` — on-device hyperbolic numerical-health stats (ball
  boundary margin, hyperboloid constraint residual, nonfinite counts),
  sampled every ``health_every=`` chunks and threshold-checked.

Catalog + reading guide: docs/observability.md.
"""

from hyperspace_tpu.telemetry.health import (  # noqa: F401
    HealthMonitor,
    health_stats,
    make_health_fn,
)
from hyperspace_tpu.telemetry.registry import (  # noqa: F401
    Registry,
    default_registry,
    install_jax_monitoring_hook,
)
from hyperspace_tpu.telemetry.trace import (  # noqa: F401
    Tracer,
    default_tracer,
    span,
)
