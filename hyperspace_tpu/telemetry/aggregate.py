"""Multihost metric aggregation: merge per-process registry exports.

ROADMAP item 1's multi-process mesh will run one registry per process;
a fleet-level view needs the processes' series REDUCED, not relabeled.
This module defines that reduction over the raw
``Registry.export()`` tuple — ``(counters, gauges, hists)`` — so the
same metrics work unchanged on one process or many:

- **counters sum** (events happened per process; the fleet total is
  their sum — ``serve/requests``, ``host_table/cache_misses``,
  ``jax/recompiles``),
- **gauges max** (levels; max is the conservative fleet reduction —
  a degraded process's ``serve/degrade_level`` or the worst
  ``serve/padded_waste_ratio`` must not be averaged away),
- **histograms merge** element-wise
  (:meth:`~hyperspace_tpu.telemetry.histogram.HistogramSnapshot.merge`
  is associative and commutative, so the fleet histogram's quantiles
  are exact, not quantile-of-quantiles).

**Shape contract** (tested): ``merge_exports([e])`` has exactly the
series names and kinds of ``e`` — aggregation never invents or drops a
family, so dashboards built against one process read a fleet scrape
unchanged (the ISSUE 17 acceptance criterion).

The JSON codec (:func:`encode` / :func:`decode`) round-trips an export
through bytes for the cross-process hop —
``parallel/multihost.gather_metric_exports`` allgathers encoded
exports and decodes per process.  Histogram snapshots serialize as
their full bucket scheme + counts, reconstructed exactly.

Render a merged export with
``telemetry.exposition.render_export(*merged, labels=...)`` — the same
format path a single process's ``/metrics`` scrape takes.
"""

from __future__ import annotations

import json
from typing import Optional

from hyperspace_tpu.telemetry.histogram import HistogramSnapshot
from hyperspace_tpu.telemetry.registry import Registry, default_registry


def export_state(registry: Optional[Registry] = None) -> tuple:
    """This process's raw ``(counters, gauges, hists)`` export."""
    reg = default_registry() if registry is None else registry
    return reg.export()


def merge_exports(exports: list) -> tuple:
    """Reduce per-process export tuples into one fleet export
    (module docstring: counters sum, gauges max, histograms merge).
    One export passes through with identical series shapes; an empty
    list is an empty export."""
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    for ctrs, gs, hs in exports:
        for name, v in ctrs.items():
            counters[name] = counters.get(name, 0) + v
        for name, v in gs.items():
            gauges[name] = v if name not in gauges else max(gauges[name], v)
        for name, snap in hs.items():
            hists[name] = (snap if name not in hists
                           else hists[name].merge(snap))
    return counters, gauges, hists


def _encode_hist(snap: HistogramSnapshot) -> dict:
    return {"counts": list(snap.counts), "count": snap.count,
            "sum": snap.sum, "vmin": snap.vmin, "vmax": snap.vmax,
            "lo": snap.lo, "hi": snap.hi, "growth": snap.growth}


def _decode_hist(d: dict) -> HistogramSnapshot:
    return HistogramSnapshot(d["counts"], d["count"], d["sum"],
                             d["vmin"], d["vmax"],
                             d["lo"], d["hi"], d["growth"])


def encode(export: tuple) -> dict:
    """One export tuple as a JSON-able dict (the wire form)."""
    counters, gauges, hists = export
    return {"counters": dict(counters), "gauges": dict(gauges),
            "hists": {k: _encode_hist(v) for k, v in hists.items()}}


def decode(d: dict) -> tuple:
    """Inverse of :func:`encode` — exact reconstruction."""
    return (dict(d["counters"]), dict(d["gauges"]),
            {k: _decode_hist(v) for k, v in d["hists"].items()})


def encode_bytes(export: tuple) -> bytes:
    """The allgather payload: compact JSON, utf-8."""
    return json.dumps(encode(export),
                      separators=(",", ":")).encode("utf-8")


def decode_bytes(data: bytes) -> tuple:
    return decode(json.loads(data.decode("utf-8")))
