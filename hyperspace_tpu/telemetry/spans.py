"""Hierarchical request spans: contextvar-propagated, async-safe,
monotonic-clock — the per-stage decomposition layer of the
observability plane (docs/observability.md "Span-level tracing").

``telemetry/trace.py``'s :class:`Tracer` nests spans **per thread**:
exactly right for the train loop (one thread, strictly nested regions)
and exactly wrong for the serve plane, where the asyncio collator
interleaves many request coroutines on one event loop — the collator
deliberately opens no tracer spans for that reason.  This module is
the async-safe sibling:

- **Spans are explicit objects** with parent/child links, keyed by the
  request id (the existing ``X-Request-Id`` join key), carrying
  ``time.perf_counter()`` stamps — never wall clock, so a stage
  duration can't be bent by NTP (the ``monotonic-clock`` hyperlint
  rule pins this).
- **Propagation is a contextvar** (:func:`current` / :func:`use` /
  :func:`request`): each asyncio task sees its own current span, so
  interleaved coroutines can never cross-contaminate trees, and
  :func:`use` carries a span across the collator's
  ``run_in_executor`` boundary into the dispatch thread.
- **The batching boundary is explicit adoption**: a collated flush is
  ONE device dispatch shared by N requests, so contextvars cannot
  express it — the collator builds one ``flush`` span and ``adopt``-s
  it into every member's tree (N requests → 1 flush → N trees holding
  the same shared subtree; child appends are lock-guarded because the
  dispatch thread writes while member coroutines read).
- **Stage histograms**: :func:`stage` observes its duration into a
  registry histogram on exit, so every span-recorded stage doubles as
  a ``/metrics`` series with no extra bookkeeping.

Everything is **off by default** and costs one module-global check
when off: :func:`stage` returns a shared no-op context manager and
:func:`root` returns None, so the serving hot path allocates nothing
(the same zero-cost contract as the tracer and the access log).
Enable with :func:`enable` (the serve CLI's ``trace=`` flag).

Trees serialize with :meth:`Span.to_dict` — offsets relative to the
tree root, durations in ms — and ride incident dumps (the flight
recorder attaches the triggering request's tree) and the slow-query
log (``slow_log=``); ``scripts/trace_report.py`` rolls a JSONL of
them into a per-stage table.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Optional

from hyperspace_tpu.telemetry import registry as telem

_current: contextvars.ContextVar = contextvars.ContextVar(
    "hyperspace_span", default=None)
_enabled = False


def enable() -> None:
    """Turn span recording on (process-global, like the tracer)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def current() -> Optional["Span"]:
    """The calling task's/thread's current span (None = no scope)."""
    return _current.get()


def active() -> bool:
    """Recording AND inside a span scope — the engine's cheap gate for
    measurement-mode work (e.g. blocking on device results so the
    ``device_compute`` stage times execution, not enqueue)."""
    return _enabled and _current.get() is not None


class Span:
    """One timed node: name, request id, perf_counter stamps, children.

    Spans are cheap plain objects — the contextvar machinery lives in
    the module functions, so a span can also be built, stamped, and
    attached entirely by hand (the lifecycle's boundary-diff stages).
    ``children`` appends are lock-guarded: the dispatch executor
    attaches stages to a flush span while member coroutines may be
    serializing their trees.
    """

    __slots__ = ("name", "request_id", "t0", "t1", "meta", "children",
                 "_lock")

    def __init__(self, name: str, request_id: Optional[str] = None,
                 meta: Optional[dict] = None):
        self.name = name
        self.request_id = request_id
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.meta = meta
        self.children: list[Span] = []
        self._lock = threading.Lock()

    def close(self) -> None:
        """Stamp the end (idempotent — first close wins)."""
        if self.t1 is None:
            self.t1 = time.perf_counter()

    @property
    def dur_ms(self) -> Optional[float]:
        return None if self.t1 is None else (self.t1 - self.t0) * 1e3

    def adopt(self, child: "Span") -> "Span":
        """Attach an existing span as a child (the flush-sharing path —
        the child may appear in several parents' trees by design)."""
        with self._lock:
            self.children.append(child)
        return child

    def add(self, name: str, t0: float, t1: float,
            meta: Optional[dict] = None) -> "Span":
        """Attach a pre-timed child (boundary-stamp stages: the caller
        already holds both perf_counter readings)."""
        c = Span(name, self.request_id, meta)
        c.t0, c.t1 = t0, t1
        return self.adopt(c)

    def to_dict(self, origin: Optional[float] = None) -> dict:
        """JSON-able tree: offsets in ms relative to ``origin`` (the
        tree root's t0 by default), durations in ms (None = the span
        never closed — itself evidence in an incident dump)."""
        if origin is None:
            origin = self.t0
        with self._lock:
            kids = list(self.children)
        d: dict = {"name": self.name,
                   "t_off_ms": round((self.t0 - origin) * 1e3, 3),
                   "dur_ms": (None if self.t1 is None
                              else round((self.t1 - self.t0) * 1e3, 3))}
        if self.request_id is not None:
            d["request_id"] = self.request_id
        if self.meta:
            d["meta"] = dict(self.meta)
        if kids:
            d["children"] = [c.to_dict(origin) for c in kids]
        return d


def root(name: str, request_id: Optional[str] = None,
         meta: Optional[dict] = None) -> Optional[Span]:
    """A new lifecycle-owned span, or None when recording is off.

    If the caller is already inside a span scope (the HTTP front
    door's request envelope), the new span is adopted as its child —
    the tree keeps the whole request story without the lifecycle
    having to know who called it."""
    if not _enabled:
        return None
    s = Span(name, request_id, meta)
    cur = _current.get()
    if cur is not None:
        cur.adopt(s)
    return s


@contextlib.contextmanager
def use(span: Optional[Span]):
    """Scope ``span`` as the current span for this task/thread — the
    executor-adoption idiom: the collator builds a flush span on the
    event loop, the dispatch thread ``use``-s it, and every
    :func:`stage` inside the engine lands in the right tree.  A None
    span scopes nothing (the disabled path composes)."""
    if span is None:
        yield None
        return
    tok = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(tok)


@contextlib.contextmanager
def request(name: str, request_id: Optional[str] = None):
    """Root request envelope + contextvar scope (the front door wraps
    each serve op in one, keyed by its X-Request-Id) — closed on exit;
    yields None when recording is off."""
    if not _enabled:
        yield None
        return
    s = Span(name, request_id)
    tok = _current.set(s)
    try:
        yield s
    finally:
        s.close()
        _current.reset(tok)


class _Stage:
    """Context manager for one child stage under the current span."""

    __slots__ = ("parent", "name", "metric", "meta", "span", "_tok")

    def __init__(self, parent: Span, name: str, metric: Optional[str],
                 meta: Optional[dict]):
        self.parent = parent
        self.name = name
        self.metric = metric
        self.meta = meta

    def __enter__(self) -> Span:
        self.span = Span(self.name, self.parent.request_id, self.meta)
        self.parent.adopt(self.span)
        self._tok = _current.set(self.span)
        return self.span

    def __exit__(self, *exc) -> None:
        self.span.close()
        _current.reset(self._tok)
        if self.metric is not None:
            # the metric name is the call site's literal (the catalog
            # rows live there); this observe is the shared plumbing
            telem.observe(self.metric, self.span.dur_ms)


_NULL = contextlib.nullcontext()


def stage(name: str, metric: Optional[str] = None,
          meta: Optional[dict] = None):
    """A timed child of the current span; observes ``metric`` (a
    registry histogram name, ms) on exit.  Off — or outside any span
    scope (prewarm, direct engine tests) — it returns a shared no-op
    context manager: zero allocation, no stray histogram samples."""
    if not _enabled:
        return _NULL
    parent = _current.get()
    if parent is None:
        return _NULL
    return _Stage(parent, name, metric, meta)
