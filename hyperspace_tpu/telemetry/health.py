"""Hyperbolic numerical-health monitor: catch divergence BEFORE the NaN.

The failure mode is documented from the start of the literature: Poincaré
embeddings drift toward the ball boundary where the conformal factor (and
every gradient through artanh) blows up (Nickel & Kiela 2017), and
Lorentz-model points drift off the hyperboloid constraint surface under
f32/bf16 accumulation until ⟨x,x⟩_L residuals amplify gradients (Chami et
al. 2019, HGCN).  Today either surfaces only as a NaN loss many chunks
after the root cause.  This module computes the leading indicators ON
DEVICE — one jitted reduction over the state, no per-step host sync —
and the loop samples it every ``health_every`` chunks:

- :func:`health_stats`: jit-safe dict of device scalars for a param
  pytree — per-manifold stats (each manifold's ``health_stats`` method:
  max/mean √c·norm and min distance-to-boundary on the ball, relative
  ⟨x,x⟩_L constraint residual on the hyperboloid, per-factor merge on
  products), a global parameter norm, a global nonfinite count, and a
  global grad/moment norm when a gradient-like tree is supplied (the
  raw per-step grads never leave the jitted step, so callers pass what
  they have — e.g. Adam's first-moment EMA — under an honest name).
- :class:`HealthMonitor`: the host-side sampler run_loop drives —
  jits the stats fn once, fetches the dict (the ONE host sync, every
  N chunks only), threshold-checks it (warn at ``boundary_eps`` margin
  / ``violation_tol`` residual / any nonfinite), logs a ``health/*``
  record, and optionally hard-aborts the run.

Threshold defaults: ``proj`` clamps f32 ball points to a margin of
``smath.ball_eps(f32) = 4e-3``, so a point pinned at the clamp sits WELL
below the default ``boundary_eps = 1e-2`` — an artificially (or
organically) boundary-clamped embedding flags immediately, while healthy
mid-ball training (margins ~1) never does.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds.base import Manifold

DEFAULT_BOUNDARY_EPS = 1e-2
DEFAULT_VIOLATION_TOL = 1e-3


def health_stats(params: Any, tags: Any = None, grads: Any = None,
                 grads_name: str = "grad_norm") -> dict:
    """Device-side health scalars for a parameter pytree (jit-safe).

    ``tags`` is either a single :class:`Manifold` (``params`` is one
    point array on it), a tag tree matching ``params`` (Manifold or
    None per leaf — the optim.tags convention), or None (Euclidean:
    norms + finiteness only).  Same-named stats from several manifold
    leaves combine via :func:`manifolds.base.reduce_health_stats` (the
    one suffix-reduction rule set, shared with products).  ``grads``
    adds a global-norm field named ``grads_name`` — pass the actual
    gradient tree where available, or a momentum/EMA tree under a name
    that says so.
    """
    from hyperspace_tpu.manifolds.base import reduce_health_stats

    leaves = [l for l in jax.tree_util.tree_leaves(params)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    out: dict = {}
    nonfinite = sum(
        (jnp.sum(~jnp.isfinite(l)) for l in leaves), jnp.zeros((), jnp.int32))
    out["nonfinite"] = nonfinite
    out["param_norm"] = jnp.sqrt(
        sum((jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves),
            jnp.zeros(())))
    collected: list[dict] = []
    if isinstance(tags, Manifold):
        collected.append(tags.health_stats(params))
    elif tags is not None:
        from hyperspace_tpu.optim.tags import map_tagged

        map_tagged(
            lambda t, p: collected.append(t.health_stats(p))
            if t is not None else None, tags, params)
    out.update(reduce_health_stats(collected))
    if grads is not None:
        gl = [g for g in jax.tree_util.tree_leaves(grads)
              if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact)]
        out[grads_name] = jnp.sqrt(
            sum((jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gl),
                jnp.zeros(())))
    return out


def make_health_fn(tags: Any = None, params_of: Optional[Callable] = None,
                   grads_of: Optional[Callable] = None,
                   grads_name: str = "grad_norm") -> Callable:
    """Build the jitted ``fn(state) -> {name: device scalar}`` run_loop
    samples.  ``params_of`` extracts the parameter tree from the train
    state (default: ``state.params`` when present, else the state
    itself); ``grads_of`` optionally extracts a gradient-like tree
    (reported as ``grads_name``)."""

    def fn(state):
        params = (params_of(state) if params_of is not None
                  else getattr(state, "params", state))
        grads = grads_of(state) if grads_of is not None else None
        return health_stats(params, tags, grads=grads,
                            grads_name=grads_name)

    return jax.jit(fn)


class HealthMonitor:
    """Sampled threshold-checker around a health fn (run_loop's hook).

    ``check(state, step, log)`` runs the jitted stats fn, fetches the
    scalars (the one host sync — callers control cadence), writes one
    JSONL record carrying ``health/*`` fields plus ``health/ok``, and
    warns (or raises ``FloatingPointError`` when ``abort=True``) when

    - any value is nonfinite / ``nonfinite > 0``,
    - any ``*boundary_margin_min`` < ``boundary_eps`` (ball points at
      the clamp — gradients through artanh are already amplified),
    - any ``*violation_max`` > ``violation_tol`` (off the hyperboloid).
    """

    def __init__(self, fn: Callable, *, boundary_eps: float =
                 DEFAULT_BOUNDARY_EPS,
                 violation_tol: float = DEFAULT_VIOLATION_TOL,
                 abort: bool = False):
        self.fn = fn
        self.boundary_eps = float(boundary_eps)
        self.violation_tol = float(violation_tol)
        self.abort = abort
        self.checks = 0
        self.warnings = 0

    def problems(self, vals: dict) -> list[str]:
        """Public re-check of a sampled dict — the divergence guard
        (resilience/guard.py) asks "did this sample cross a threshold"
        without re-running the device fn."""
        return self._problems(vals)

    def _problems(self, vals: dict) -> list[str]:
        import math

        probs = []
        for k, v in vals.items():
            if not math.isfinite(v):
                probs.append(f"{k} is {v}")
            elif k == "nonfinite" and v > 0:
                probs.append(f"{int(v)} nonfinite values in state")
            elif k.endswith("boundary_margin_min") and v < self.boundary_eps:
                probs.append(f"{k}={v:.2e} < boundary_eps="
                             f"{self.boundary_eps:.0e}")
            elif k.endswith("violation_max") and v > self.violation_tol:
                probs.append(f"{k}={v:.2e} > violation_tol="
                             f"{self.violation_tol:.0e}")
        return probs

    def check(self, state: Any, step: int, log=None) -> dict:
        """Sample once; returns the host-side {name: float} dict."""
        from hyperspace_tpu.telemetry import registry

        device_stats = self.fn(state)
        vals = {k: float(v) for k, v in
                jax.device_get(device_stats).items()}
        self.checks += 1
        registry.inc("health/checks")
        problems = self._problems(vals)
        if log is not None:
            rec = {f"health/{k}": v for k, v in vals.items()}
            rec["health/ok"] = not problems
            log.log(step, **rec)
        if problems:
            self.warnings += 1
            registry.inc("health/warnings")
            msg = (f"[health] step {step}: " + "; ".join(problems))
            print(msg, flush=True)
            if self.abort:
                raise FloatingPointError(msg)
        return vals
