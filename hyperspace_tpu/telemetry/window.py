"""Windowed SLOs: rolling p50/p95/p99 + rates from snapshot ring deltas.

The PR 7 histograms are process-cumulative: ``hist/serve/e2e_ms`` in
``/v1/stats`` answers "what has latency been since the process
started", but an operator (and ROADMAP item 4's rollover bench) needs
"what is latency NOW" — a p99 that a 2-hour-old warmup spike can no
longer drag, and shed/deadline/error **rates** rather than counts.

:class:`SloWindow` keeps a bounded ring of ``(t, histogram snapshots,
counters)`` captures, at most one per ``window_s / slots`` seconds
(``tick()`` is a clock compare until the slot turns over — hot-path
cheap), and :meth:`report` subtracts the oldest in-window capture from
a fresh one: the delta histogram (``HistogramSnapshot.since`` — the
PR 7 snapshots already subtract) carries the window's OWN distribution,
so the reported p50/p95/p99 are computed from ring deltas, never from
the run-cumulative totals (the acceptance contract, tested against
exact percentiles within the histogram's ~4.9 % bound).

Consumers: ``batcher.stats()`` (→ ``/v1/stats`` and the serve-exit
summary) and the serve CLIs' stderr summary line; ``/metrics`` carries
the underlying cumulative histogram (a scraper computes its own
windows via PromQL).  :meth:`latency_pressure` is the optional
latency-aware signal for the degradation ladder (``slo_ms=`` on the
serve CLI): pressure 1.0 while the windowed p99 sits past the SLO —
today the ladder reacts to queue depth only, which misses the
slow-but-not-queueing overload mode.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Sequence

from hyperspace_tpu.telemetry.registry import Registry, default_registry

DEFAULT_WINDOW_S = 60.0
DEFAULT_SLOTS = 12

# the serve counters whose window-deltas become rates in report();
# callers may extend, but these are the SLO trio + the volume base
DEFAULT_COUNTERS = ("serve/requests", "serve/shed",
                    "serve/deadline_exceeded", "serve/errors")
DEFAULT_HISTS = ("serve/e2e_ms",)


class SloWindow:
    """Rolling-window view over registry histograms + counters."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S, *,
                 slots: int = DEFAULT_SLOTS,
                 registry: Optional[Registry] = None,
                 hist_names: Sequence[str] = DEFAULT_HISTS,
                 counter_names: Sequence[str] = DEFAULT_COUNTERS,
                 now: Optional[float] = None):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0; got {window_s}")
        if slots < 2:
            raise ValueError(f"slots must be >= 2; got {slots}")
        self.window_s = float(window_s)
        self.slot_s = self.window_s / int(slots)
        self._registry = registry
        self.hist_names = tuple(hist_names)
        self.counter_names = tuple(counter_names)
        self._lock = threading.Lock()
        # ring of (t, {hist: snapshot}, {counter: value}); bounded at
        # slots+1 so one capture always predates the window's left edge
        self._ring: collections.deque = collections.deque(
            maxlen=int(slots) + 1)
        self._next_slot = 0.0
        # latency_pressure caches one report per slot: the admission
        # path reads it per request, and a full delta per admit would
        # put a histogram subtraction on the hot path
        self._pressure_cache: tuple = (-float("inf"), 0.0)  # (until, p99)
        # prime the ring at construction so traffic in the FIRST slot
        # is already a delta against a baseline — without this, the
        # first capture (taken after the first request) would exclude
        # everything before it.  ``now`` pins the clock for tests.
        now = time.monotonic() if now is None else now
        self._next_slot = now + self.slot_s
        self._ring.append(self._capture(now))

    @classmethod
    def for_tenant(cls, tenant: str, window_s: float = DEFAULT_WINDOW_S,
                   **kw) -> "SloWindow":
        """A window over one tenant's metric series: the default hist/
        counter names with the tenant suffix the batcher double-writes
        (exposition.tenant_metric), so a multi-tenant process gets one
        independent SLO view per tenant instead of N windows all
        reading the shared aggregates."""
        from hyperspace_tpu.telemetry.exposition import tenant_metric

        return cls(
            window_s,
            hist_names=tuple(tenant_metric(n, tenant)
                             for n in DEFAULT_HISTS),
            counter_names=tuple(tenant_metric(n, tenant)
                                for n in DEFAULT_COUNTERS),
            **kw)

    def _reg(self) -> Registry:
        return self._registry or default_registry()

    def _capture(self, now: float) -> tuple:
        reg = self._reg()
        counters, _gauges, hists = reg.export(hist_names=self.hist_names)
        return (now, hists,
                {n: counters.get(n, 0) for n in self.counter_names})

    def tick(self, now: Optional[float] = None) -> None:
        """Advance the ring (at most one capture per slot).  Call per
        request completion and per report — one clock read + one float
        compare until the slot turns over."""
        now = time.monotonic() if now is None else now
        if now < self._next_slot:
            return
        with self._lock:
            if now < self._next_slot:  # raced: the other caller captured
                return
            self._next_slot = now + self.slot_s
            self._ring.append(self._capture(now))

    def report(self, now: Optional[float] = None) -> dict:
        """The window's SLO view, computed from ring deltas:

        ``{"window_s": elapsed, "e2e_ms": {count, p50, p95, p99} |
        None, "rate_qps": r, "shed_rate": r, "deadline_rate": r,
        "error_rate": r}`` — rates are per-second over the window's
        actual elapsed span.  Before any traffic (empty ring / zero
        elapsed) the distribution is None and rates 0."""
        now = time.monotonic() if now is None else now
        self.tick(now)
        with self._lock:
            ring = list(self._ring)
        head = self._capture(now)
        # baseline = the oldest capture still inside (or bounding) the
        # window; the +slot slack keeps the span from collapsing right
        # after a slot turnover
        base = None
        for entry in ring:
            if now - entry[0] <= self.window_s + self.slot_s:
                base = entry
                break
        if base is None or now <= base[0]:
            return {"window_s": 0.0, "e2e_ms": None, "rate_qps": 0.0,
                    "shed_rate": 0.0, "deadline_rate": 0.0,
                    "error_rate": 0.0}
        elapsed = now - base[0]
        out: dict = {"window_s": round(elapsed, 3)}
        e2e = None
        for name in self.hist_names:
            cur = head[1].get(name)
            if cur is None:
                continue
            prior = base[1].get(name)
            delta = cur.since(prior) if prior is not None else cur
            if delta.count <= 0:
                continue
            e2e = {"count": delta.count}
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                v = delta.quantile(q)
                e2e[key] = None if v is None else round(v, 6)
            break  # the summary block reports the first (primary) hist
        out["e2e_ms"] = e2e

        def rate(counter: str) -> float:
            # resolve by BASE name: a per-tenant window is configured
            # with tenant-suffixed counter names (``serve/requests@
            # tenant=en`` — telemetry/exposition.py's label scheme), and
            # its rates must read those, not the all-tenant aggregates
            name = next((n for n in self.counter_names
                         if n == counter or n.startswith(counter + "@")),
                        counter)
            d = head[2].get(name, 0) - base[2].get(name, 0)
            return round(max(d, 0) / elapsed, 4)

        out["rate_qps"] = rate("serve/requests")
        out["shed_rate"] = rate("serve/shed")
        out["deadline_rate"] = rate("serve/deadline_exceeded")
        out["error_rate"] = rate("serve/errors")
        return out

    def latency_pressure(self, slo_ms: float,
                         now: Optional[float] = None) -> float:
        """1.0 while the windowed ``e2e_ms`` p99 exceeds ``slo_ms``,
        else 0.0 — the ladder's optional latency signal.  Cached per
        slot (module docstring); an empty window reads 0 (no evidence
        is never pressure)."""
        if slo_ms <= 0:
            return 0.0
        now = time.monotonic() if now is None else now
        valid_until, p99 = self._pressure_cache
        if now >= valid_until:
            rep = self.report(now)
            p99 = (rep["e2e_ms"] or {}).get("p99") or 0.0
            self._pressure_cache = (now + self.slot_s, p99)
        return 1.0 if p99 > slo_ms else 0.0
