"""Streaming latency histograms — the third metric kind beside
counters and gauges.

ROADMAP item 3 makes **p50/p95/p99 latency at fixed offered qps** the
serve headline, but ``inc``/``set_gauge`` can only express sums and
levels: no percentile can be measured from them, and averaging a
counter of seconds hides exactly the tail the SLO cares about.  This
module adds the distributional kind the registry lacked:

- **Fixed log-spaced buckets** (Prometheus-style static boundaries,
  HDR-histogram-style log spacing): bucket upper bounds grow by
  ``GROWTH`` (default 1.1) from ``LO`` to ``HI`` (defaults 1e-3..1e5 —
  1 µs to 100 s when values are milliseconds, the convention every
  call site uses).  A value's quantile estimate is its bucket's
  geometric midpoint, so the relative error is bounded by
  ``sqrt(GROWTH) - 1`` ≈ **4.9%** — the ~5% contract the tests pin.
- **Thread-safe, dependency-free observe**: one lock, one ``math.log``,
  one list increment — no numpy, no device work, safe on the serve and
  train hot paths (the same always-on budget as ``inc``).
- **Mergeable snapshots**: a :class:`HistogramSnapshot` is a frozen
  bucket-count vector plus count/sum/min/max; ``merge`` is
  element-wise addition (associative — shard histograms combine in any
  order) and ``since`` subtracts a baseline snapshot, which is how the
  registry reports per-interval/per-leg latency deltas
  (``Registry.mark``/``snapshot`` — e.g. bench_serve's per-bucket
  percentiles).

The module-level :func:`observe` is the call sites' one-liner beside
``registry.inc``/``set_gauge``; the registry surfaces every observed
histogram as a ``hist/<name>`` entry (count/sum/min/max/p50/p90/p95/
p99) in ``Registry.snapshot``, so JSONL records, ``telemetry_summary``,
and bench artifacts pick the distributions up with no new plumbing.
Histogram names are cataloged in docs/observability.md ("Histograms"
section) — the ``telemetry-catalog`` lint scans ``observe(`` writes
like any other registry write.
"""

from __future__ import annotations

import math
import threading
from typing import Optional, Sequence

# default bucket scheme: ~5% relative error over 8 decades.  With the
# call-site convention of milliseconds this spans 1 µs .. 100 s; values
# outside land in the underflow/overflow buckets and their quantile
# estimates clamp to the exact observed min/max.
DEFAULT_LO = 1e-3
DEFAULT_HI = 1e5
DEFAULT_GROWTH = 1.1

DEFAULT_QUANTILES = (0.5, 0.9, 0.95, 0.99)

_scheme_cache: dict = {}


def _num_buckets(lo: float, hi: float, growth: float) -> int:
    """Bucket count for the finite range (cached per scheme)."""
    key = (lo, hi, growth)
    n = _scheme_cache.get(key)
    if n is None:
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError(
                f"bad histogram scheme lo={lo} hi={hi} growth={growth}")
        n = _scheme_cache[key] = int(
            math.ceil(math.log(hi / lo) / math.log(growth)))
    return n


class HistogramSnapshot:
    """Frozen view of a histogram: bucket counts + count/sum/min/max.

    ``counts`` has ``len == num_buckets + 2``: index 0 is the underflow
    bucket (values < lo, incl. non-positive), the last is overflow
    (values >= hi).  Snapshots with the same (lo, hi, growth) scheme
    merge associatively and subtract (``since``) — the registry's
    baseline-delta mechanics reuse the same arithmetic sharded
    histogram combination would.
    """

    __slots__ = ("counts", "count", "sum", "vmin", "vmax",
                 "lo", "hi", "growth")

    def __init__(self, counts: Sequence[int], count: int, total: float,
                 vmin: Optional[float], vmax: Optional[float],
                 lo: float, hi: float, growth: float):
        self.counts = tuple(counts)
        self.count = int(count)
        self.sum = float(total)
        self.vmin = vmin
        self.vmax = vmax
        self.lo = lo
        self.hi = hi
        self.growth = growth

    def _check_scheme(self, other: "HistogramSnapshot") -> None:
        if (self.lo, self.hi, self.growth) != (other.lo, other.hi,
                                               other.growth):
            raise ValueError(
                "histogram scheme mismatch: "
                f"{(self.lo, self.hi, self.growth)} vs "
                f"{(other.lo, other.hi, other.growth)}")

    def merge(self, other: "HistogramSnapshot") -> "HistogramSnapshot":
        """Element-wise combine (associative, commutative)."""
        self._check_scheme(other)
        mins = [m for m in (self.vmin, other.vmin) if m is not None]
        maxs = [m for m in (self.vmax, other.vmax) if m is not None]
        return HistogramSnapshot(
            [a + b for a, b in zip(self.counts, other.counts)],
            self.count + other.count, self.sum + other.sum,
            min(mins) if mins else None, max(maxs) if maxs else None,
            self.lo, self.hi, self.growth)

    def since(self, baseline: "HistogramSnapshot") -> "HistogramSnapshot":
        """The delta histogram ``self - baseline`` (baseline must be an
        earlier snapshot of the same histogram).  The exact window
        extremes are not recoverable from bucket counts, so min/max
        tighten to the delta's bucket envelope: the lower/upper edge of
        the lowest/highest nonzero delta bucket, intersected with the
        lifetime extremes — a pre-mark spike can no longer surface as
        every later interval's max (the stale-exclusion contract the
        registry's baseline gauges follow).  Under/overflow buckets
        have no finite edge and fall back to the lifetime extreme."""
        self._check_scheme(baseline)
        counts = [max(a - b, 0)
                  for a, b in zip(self.counts, baseline.counts)]
        count = max(self.count - baseline.count, 0)
        # same clamping as the bucket counts: a stale baseline (e.g.
        # taken before a reset) must degrade to zeros, never to a
        # negative sum beside a positive count (durations are >= 0)
        total = max(self.sum - baseline.sum, 0.0) if count else 0.0
        vmin: Optional[float] = None
        vmax: Optional[float] = None
        if count > 0:
            n = len(counts) - 2
            first = next(i for i, c in enumerate(counts) if c)
            last = next(i for i in reversed(range(len(counts)))
                        if counts[i])
            # bucket i spans [lo*g^(i-1), lo*g^i), except values >= hi
            # always overflow — so every finite edge caps at hi
            lo_edge = (None if first == 0
                       else min(self.lo * self.growth ** (first - 1),
                                self.hi))
            hi_edge = (None if last == n + 1
                       else min(self.lo * self.growth ** last, self.hi))
            vmin = (self.vmin if lo_edge is None
                    else lo_edge if self.vmin is None
                    else max(lo_edge, self.vmin))
            vmax = (self.vmax if hi_edge is None
                    else hi_edge if self.vmax is None
                    else min(hi_edge, self.vmax))
        return HistogramSnapshot(counts, count, total,
                                 vmin, vmax, self.lo, self.hi,
                                 self.growth)

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (None when empty); ≤ ~5% relative error
        for in-range values (geometric bucket midpoint), exact at the
        observed min/max (the estimate clamps to them)."""
        if self.count <= 0:
            return None
        q = min(max(float(q), 0.0), 1.0)
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                idx = i
                break
        n = len(self.counts) - 2
        if idx == 0:
            est = self.vmin if self.vmin is not None else self.lo
        elif idx == n + 1:
            est = self.vmax if self.vmax is not None else self.hi
        else:
            # bucket idx spans [lo*g^(idx-1), lo*g^idx): geometric mid
            est = self.lo * self.growth ** (idx - 0.5)
        if self.vmin is not None:
            est = max(est, self.vmin)
        if self.vmax is not None:
            est = min(est, self.vmax)
        return est

    def fields(self, quantiles: Sequence[float] = DEFAULT_QUANTILES
               ) -> dict:
        """The compact JSON-safe dict the registry surfaces as a
        ``hist/<name>`` entry: count/sum/min/max plus the standard
        quantiles (``p50``..).  Empty histogram → count 0, None stats —
        the tested empty-snapshot shape."""
        out = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": None if self.vmin is None else round(self.vmin, 6),
            "max": None if self.vmax is None else round(self.vmax, 6),
        }
        for q in quantiles:
            v = self.quantile(q)
            key = f"p{q * 100:g}".replace(".", "_")
            out[key] = None if v is None else round(v, 6)
        return out


class Histogram:
    """Thread-safe streaming histogram over fixed log-spaced buckets."""

    __slots__ = ("_lock", "_counts", "_count", "_sum", "_min", "_max",
                 "lo", "hi", "growth", "_n", "_log_lo", "_inv_log_g")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 growth: float = DEFAULT_GROWTH):
        self._n = _num_buckets(lo, hi, growth)
        self.lo = lo
        self.hi = hi
        self.growth = growth
        self._log_lo = math.log(lo)
        self._inv_log_g = 1.0 / math.log(growth)
        self._lock = threading.Lock()
        self._counts = [0] * (self._n + 2)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, value: float) -> None:
        """Record one value (a latency in the call sites' convention)."""
        v = float(value)
        if v != v:  # NaN never lands in a bucket — drop, don't poison
            return
        if v < self.lo:
            idx = 0
        elif v >= self.hi:
            idx = self._n + 1
        else:
            # floor(log(v/lo)/log(g)); float fudge at an exact boundary
            # moves the value one bucket over — within the error bound
            idx = 1 + int((math.log(v) - self._log_lo) * self._inv_log_g)
            idx = min(max(idx, 1), self._n)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def snapshot(self) -> HistogramSnapshot:
        """Consistent point-in-time snapshot (mergeable, subtractable)."""
        with self._lock:
            return HistogramSnapshot(
                list(self._counts), self._count, self._sum,
                self._min, self._max, self.lo, self.hi, self.growth)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (self._n + 2)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None


def observe(name: str, value: float) -> None:
    """Record ``value`` into the default registry's histogram ``name``
    — the module-level one-liner beside ``registry.inc`` /
    ``registry.set_gauge`` (also re-exported there)."""
    from hyperspace_tpu.telemetry import registry as _registry

    _registry.default_registry().observe(name, value)
