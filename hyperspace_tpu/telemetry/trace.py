"""Host-level trace spans: ``with span("dispatch"): ...``.

PR 1 made the hot path opaque from the outside: K steps disappear into
one ``lax.scan`` dispatch, and the JSONL stream says nothing about WHERE
wall-clock time went between two log boundaries — host prep, a prefetch
stall, the dispatch itself, or a checkpoint write.  ``train/profiling
.trace`` answers the on-device question (XLA ops, via jax.profiler);
this module answers the host-side one with nested wall-clock spans that

- cost ~nothing when disabled: the module-level :func:`span` returns a
  shared ``nullcontext`` singleton without allocating (one attribute
  check per call — the tested disabled-mode contract), so call sites
  stay unconditionally instrumented;
- aggregate per span name between JSONL log boundaries —
  ``Tracer.flush_fields()`` → ``{"span/<name>_s": seconds, ...}`` —
  one group of fields per log record, no per-span I/O;
- optionally retain every event for a Chrome/Perfetto ``trace_events``
  dump (:meth:`Tracer.dump_chrome_trace`): load the JSON in
  https://ui.perfetto.dev to see the nested host timeline next to the
  numbers the JSONL already carries.

Span names in use are cataloged in docs/observability.md (``prep``,
``prefetch_wait``, ``dispatch``, ``metrics_flush``, ``ckpt_save``,
``eval``); the catalog lint covers counters only, but keep the doc in
step when adding span call sites.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Optional

# one reusable, stateless disabled-path context manager: entering it is
# a couple of attribute lookups and no allocation
_NULL = contextlib.nullcontext()

# retention cap for the Chrome dump event list — a runaway span loop
# must not eat the host; ~1e6 events ≈ 100 MB JSON, far beyond any
# useful trace.  A ring (deque maxlen): the OLDEST events are evicted,
# because the dump's crash-diagnosis job needs the timeline's TAIL —
# what happened just before the failure (drop count kept for honesty).
_MAX_EVENTS = 1_000_000


class _Span:
    """The enabled-path context manager (one fresh object per span —
    spans nest and cross threads, so no singleton here).

    ``args`` is an optional metadata dict carried into the Chrome-trace
    event (batch size, bucket, cache hits, step — docs/observability.md)
    so Perfetto can correlate spans with load.  The dict is held by
    REFERENCE and read at ``__exit__``: a call site may create it with
    what it knows up front and fill in the rest (e.g. cache hits) before
    the span closes."""

    __slots__ = ("_tracer", "_name", "_t0", "_args")

    def __init__(self, tracer: "Tracer", name: str, args=None):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer._record(self._name, self._t0, time.perf_counter(),
                             self._args)
        return False


class Tracer:
    """Wall-clock span recorder: per-name aggregates (always, when
    enabled) + the full event list (only when ``keep_events``)."""

    def __init__(self, *, enabled: bool = False, keep_events: bool = False):
        self.enabled = enabled
        self.keep_events = keep_events
        self._lock = threading.Lock()
        self._agg: dict[str, float] = {}        # since last flush
        self._agg_n: dict[str, int] = {}
        self._total: dict[str, float] = {}      # run-cumulative
        self._total_n: dict[str, int] = {}
        # (name, t0, t1, tid, args) ring — full, oldest events evict first
        self._events: collections.deque = collections.deque(
            maxlen=_MAX_EVENTS)
        self._dropped = 0

    # --- recording ------------------------------------------------------------

    def span(self, name: str, args: Optional[dict] = None):
        """Context manager timing one ``name`` span; nests freely.
        ``args`` (optional metadata dict) rides into the Chrome dump."""
        if not self.enabled:
            return _NULL
        return _Span(self, name, args)

    def record_span(self, name: str, t0: float, t1: float,
                    args: Optional[dict] = None) -> None:
        """Record one completed span from explicit timestamps — for call
        sites that only know after the fact whether the work really
        happened (e.g. an interval-gated checkpoint save)."""
        self._record(name, t0, t1, args)

    def _record(self, name: str, t0: float, t1: float,
                args: Optional[dict] = None) -> None:
        dur = t1 - t0
        with self._lock:
            self._agg[name] = self._agg.get(name, 0.0) + dur
            self._agg_n[name] = self._agg_n.get(name, 0) + 1
            self._total[name] = self._total.get(name, 0.0) + dur
            self._total_n[name] = self._total_n.get(name, 0) + 1
            if self.keep_events:
                if len(self._events) == self._events.maxlen:
                    self._dropped += 1  # deque evicts the oldest
                self._events.append(
                    (name, t0, t1, threading.get_ident(), args))

    def reset(self) -> None:
        """Drop all aggregates/events (tests; a new run in-process).
        Like the registry, a tracer is otherwise process-cumulative."""
        with self._lock:
            self._agg.clear()
            self._agg_n.clear()
            self._total.clear()
            self._total_n.clear()
            self._events.clear()
            self._dropped = 0

    # --- reading --------------------------------------------------------------

    def flush_fields(self, prefix: str = "span/") -> dict:
        """``{prefix<name>_s: seconds_since_last_flush}`` and reset the
        boundary aggregates (cumulative totals are untouched) — the
        fields a JSONL log record carries for its interval."""
        with self._lock:
            out = {f"{prefix}{k}_s": round(v, 6)
                   for k, v in self._agg.items()}
            self._agg.clear()
            self._agg_n.clear()
        return out

    def total_fields(self, prefix: str = "span/") -> dict:
        """Run-cumulative ``{prefix<name>_s, prefix<name>_n}`` — the
        telemetry_summary payload."""
        with self._lock:
            out = {}
            for k, v in self._total.items():
                out[f"{prefix}{k}_s"] = round(v, 6)
                out[f"{prefix}{k}_n"] = self._total_n[k]
        return out

    # --- Chrome/Perfetto dump -------------------------------------------------

    def dump_chrome_trace(self, path: str) -> int:
        """Write retained events as Chrome ``trace_events`` JSON
        (Perfetto-loadable); returns the number of events written.

        Complete "X" events on one pid, one tid per host thread —
        nesting is by time containment, exactly how the spans nested.
        DRAINS the retained events: a later dump (a second run in the
        same process) starts from a clean timeline and the memory is
        released rather than held to the retention cap for the process
        lifetime.
        """
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            self._events.clear()
            self._dropped = 0
        pid = os.getpid()
        tids: dict[int, int] = {}
        trace = []
        for name, t0, t1, ident, args in events:
            tid = tids.setdefault(ident, len(tids))
            ev = {
                "name": name, "ph": "X", "pid": pid, "tid": tid,
                "ts": round(t0 * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
            }
            if args:
                # the optional metadata payload (batch size, bucket,
                # step, cache hits) — Perfetto shows it on click, so a
                # slow span is attributable to its load
                ev["args"] = args
            trace.append(ev)
        doc = {"traceEvents": trace, "displayTimeUnit": "ms",
               "otherData": {"source": "hyperspace_tpu.telemetry",
                             "dropped_events": dropped}}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(trace)


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def default_tracer() -> Tracer:
    """The process-wide tracer every module-level :func:`span` feeds
    (disabled until :func:`enable` — zero-cost by default)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def tracing() -> bool:
    """True when the default tracer is recording — the guard hot call
    sites use to skip building a span-``args`` dict entirely on the
    disabled path (``span()`` itself is allocation-free when disabled,
    but a caller-built metadata dict would not be)."""
    t = _tracer
    return t is not None and t.enabled


def span(name: str, args: Optional[dict] = None):
    """``with span("prep"): ...`` on the default tracer.

    Call sites keep this unconditionally: disabled (the default) it
    returns the shared nullcontext without allocating.  ``args`` is the
    optional metadata dict for the Chrome dump — held by reference, so
    a call site may fill it in before the span exits.
    """
    t = _tracer
    if t is None or not t.enabled:
        return _NULL
    return _Span(t, name, args)


def enable(*, keep_events: bool = False) -> Tracer:
    """Turn the default tracer on (``keep_events`` retains the full
    event list for a Chrome dump) and return it.  ``keep_events`` is
    SET, not or-ed: a later run without ``trace_out`` must be able to
    turn retention back off (the CLI and run_loop both derive the flag
    from the same run config, so duplicate enables within one run
    always agree)."""
    t = default_tracer()
    t.enabled = True
    t.keep_events = keep_events
    return t


def disable() -> None:
    t = default_tracer()
    t.enabled = False
