"""Process-wide counter/gauge registry — the ONE home of run counters.

PR 1 left the hot path's bookkeeping scattered: prep-cache hits were a
``print`` per event, prefetch stalls were invisible, checkpoint cost was
nowhere, and recompiles only showed up as mysterious wall-clock cliffs.
This registry replaces the ad-hoc lines with named counters/gauges that
(1) any module can bump with one cheap dict-op (no device work, no
host sync — safe on the per-dispatch path), (2) the training loop
snapshots into every JSONL log record (``ctr/*`` fields) and into one
final ``telemetry_summary`` record, and (3) the bench can read directly.

The COUNTER CATALOG lives in docs/observability.md; every name
incremented anywhere in the package must be documented there —
``scripts/check_telemetry_catalog.py`` (run inside the test suite)
fails the build otherwise.  Add the doc row when you add the counter.

Counters are monotonic sums (floats allowed: seconds accumulate);
gauges are last-write-wins levels (queue depth, bytes on disk);
histograms (:mod:`hyperspace_tpu.telemetry.histogram` — the third
kind, ``observe(name, value)``) are streaming latency distributions
surfaced as ``hist/<name>`` snapshot entries with count/sum/min/max
and p50/p90/p95/p99.  All ops are lock-guarded — the prefetch worker
thread increments concurrently with the training loop.

``install_jax_monitoring_hook`` subscribes to :mod:`jax.monitoring`'s
duration events and turns backend compiles into ``jax/recompiles`` /
``jax/compile_s`` — the counter that catches a shape-unstable stepper
recompiling every chunk (the failure the chunked loop's donation +
static scan length is supposed to rule out).  With the persistent
compilation cache active (:mod:`hyperspace_tpu.compile_cache`) the same
hook also counts ``jax/compile_cache_hit`` (executables deserialized
from disk — the backend compile never ran) and
``jax/compile_cache_miss`` (backend compiles while the cache was
enabled; each writes a new entry), so cache hit rates ride into every
JSONL record and bench artifact for free.
"""

from __future__ import annotations

import threading
from typing import Optional

_BACKEND_COMPILE_SUBSTR = "backend_compile"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


class Registry:
    """Named monotonic counters + last-write gauges, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        # gauge -> (value, write seq): the seq lets a per-run snapshot
        # exclude stale gauges a PRIOR in-process run set (see mark())
        self._gauges: dict[str, tuple] = {}
        self._hists: dict = {}  # name -> histogram.Histogram
        self._seq = 0

    def inc(self, name: str, value: float = 1) -> None:
        """Add ``value`` to counter ``name`` (creating it at 0)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._seq += 1
            self._gauges[name] = (value, self._seq)

    def observe(self, name: str, value: float) -> None:
        """Record ``value`` into streaming histogram ``name`` (created
        on first observe).  The registry lock only guards the name
        lookup; the histogram's own lock guards the counts — an
        ``observe`` never blocks behind a ``snapshot`` of OTHER names.
        The price: an observe racing :meth:`reset` may land in the
        cleared epoch and be dropped with it (unlike ``inc``, which is
        reset-atomic) — fine for reset's tests/new-run use."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                from hyperspace_tpu.telemetry.histogram import Histogram

                h = self._hists[name] = Histogram()
        h.observe(value)

    def get(self, name: str) -> float:
        """Current counter value (0 if never incremented); gauges via
        :meth:`snapshot`."""
        with self._lock:
            return self._counters.get(name, 0)

    def mark(self) -> dict:
        """Opaque per-run baseline for :meth:`snapshot`: counter values
        plus the gauge write sequence at capture time.  A consumer
        reporting per-run numbers from this process-cumulative registry
        (run_loop in library use) captures one at run start."""
        with self._lock:
            counters = dict(self._counters)
            seq = self._seq
            hists = dict(self._hists)
        # histogram snapshots are taken OUTSIDE the registry lock (each
        # histogram has its own) — same reason observe() releases it
        return {"counters": counters, "seq": seq,
                "hists": {k: h.snapshot() for k, h in hists.items()}}

    def snapshot(self, prefix: str = "", baseline: Optional[dict] = None
                 ) -> dict:
        """One consistent {prefix+name: value} view of every counter and
        gauge — the dict the loop merges into JSONL records.  With a
        ``baseline`` (a prior :meth:`mark`) counters are reported as
        deltas since the capture, and gauges are included only if
        WRITTEN since it — a stale level from a previous in-process run
        (e.g. its ``ckpt/bytes``) never masquerades as this run's.

        Histograms ride along as ``hist/<name>`` entries (count/sum/
        min/max/p50..p99 dicts — :meth:`HistogramSnapshot.fields`).
        They keep the fixed ``hist/`` namespace rather than taking
        ``prefix`` (the loop's ``ctr/`` prefix means "counter"; these
        are not), so JSONL records and bench artifacts carry e.g.
        ``hist/serve/e2e_ms`` verbatim.  With a baseline, each
        histogram is the DELTA distribution since the mark, and
        histograms with no observations since it are omitted — the
        same stale-exclusion contract as gauges."""
        with self._lock:
            if baseline is None:
                out = {prefix + k: v for k, v in self._counters.items()}
                out.update(
                    (prefix + k, v) for k, (v, _s) in self._gauges.items())
            else:
                base_c, base_s = baseline["counters"], baseline["seq"]
                out = {prefix + k: v - base_c.get(k, 0)
                       for k, v in self._counters.items()}
                out.update((prefix + k, v)
                           for k, (v, s) in self._gauges.items()
                           if s > base_s)
            hists = dict(self._hists)
        base_h = (baseline or {}).get("hists", {})
        for name, h in hists.items():
            snap = h.snapshot()
            if baseline is not None:
                prior = base_h.get(name)
                if prior is not None:
                    snap = snap.since(prior)
                if snap.count <= 0:
                    continue
            out["hist/" + name] = snap.fields()
        return out

    def export(self, hist_names=None) -> tuple[dict, dict, dict]:
        """``(counters, gauges, hist_snapshots)`` — the raw state the
        Prometheus exposition (:mod:`hyperspace_tpu.telemetry.
        exposition`) and the SLO window (:mod:`~.window`) render from.
        Unlike :meth:`snapshot`, histograms come back as
        :class:`~hyperspace_tpu.telemetry.histogram.HistogramSnapshot`
        objects (bucket counts included — cumulative ``le`` buckets and
        ring-delta subtraction both need the vector, not the summary
        fields) and gauges lose their write-seq bookkeeping.
        ``hist_names`` (a container) limits which histograms are
        snapshotted — the SLO window captures one histogram per 5 s
        slot and per stats read, and snapshotting every ~285-bucket
        vector only to discard them would tax the admission path."""
        with self._lock:
            counters = dict(self._counters)
            gauges = {k: v for k, (v, _s) in self._gauges.items()}
            hists = dict(self._hists)
        if hist_names is not None:
            hists = {k: h for k, h in hists.items() if k in hist_names}
        # snapshots OUTSIDE the registry lock (each histogram has its
        # own) — the same ordering rule as mark()
        return counters, gauges, {k: h.snapshot() for k, h in hists.items()}

    def reset(self) -> None:
        """Drop every counter/gauge/histogram (tests; a new run
        in-process)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._seq = 0


_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry()
    return _default


def inc(name: str, value: float = 1) -> None:
    """Bump a counter on the default registry (the call sites' one-liner)."""
    default_registry().inc(name, value)


def set_gauge(name: str, value: float) -> None:
    default_registry().set_gauge(name, value)


def observe(name: str, value: float) -> None:
    """Record one value into histogram ``name`` on the default registry
    (latencies in ms by call-site convention — telemetry/histogram.py)."""
    default_registry().observe(name, value)


def snapshot(prefix: str = "") -> dict:
    return default_registry().snapshot(prefix)


_hook_installed = False


def install_jax_monitoring_hook() -> None:
    """Route jax's compile-duration events into the default registry.

    Idempotent (one listener per process — jax.monitoring offers no
    per-listener removal).  The listener resolves ``default_registry()``
    at event time, so a test that swaps/resets the registry still sees
    fresh counts.  Counts ``/jax/core/compile/backend_compile_duration``
    events: one per XLA backend compile, i.e. recompiles once the run's
    steady state is reached.  The persistent-cache counters (module
    docstring) come from the cache's own explicit events: a
    ``cache_hits`` event is an executable deserialized from disk, a
    ``cache_misses`` event a compile the cache could not serve.  NOTE
    on this jax's accounting: a persistent-cache HIT still fires the
    ``backend_compile`` duration event (it times the deserialization),
    so ``jax/recompiles`` counts executable *materializations* either
    way — the cache's win reads in ``jax/compile_s`` collapsing (~20×
    on this image) and in the hit counter, not in a lower recompile
    count.  In-process warm executables fire nothing, so the flat-once-
    warm contracts are unchanged.
    """
    global _hook_installed
    if _hook_installed:
        return
    try:
        import jax.monitoring as _mon

        def _on_duration(event: str, duration: float, **_kw) -> None:
            if _BACKEND_COMPILE_SUBSTR in event:
                reg = default_registry()
                reg.inc("jax/recompiles")
                reg.inc("jax/compile_s", float(duration))

        def _on_event(event: str, **_kw) -> None:
            if event == _CACHE_HIT_EVENT:
                default_registry().inc("jax/compile_cache_hit")
            elif event == _CACHE_MISS_EVENT:
                default_registry().inc("jax/compile_cache_miss")

        _mon.register_event_duration_secs_listener(_on_duration)
        _mon.register_event_listener(_on_event)
        _hook_installed = True
    except Exception:  # noqa: BLE001  # hyperlint: disable=swallow-base-exception — jax.monitoring absent/renamed: recompile counting is best-effort by contract (telemetry must never sink a run)
        pass
