"""Evaluation metrics (SURVEY.md §3.5): ROC-AUC, accuracy, F1.

ROC-AUC is the [B] north-star quality metric for HGCN link prediction.
Implemented rank-based (Mann–Whitney U) with tie-averaged ranks — exactly
what sklearn computes, but dependency-free and usable on device outputs.
"""

from __future__ import annotations

import numpy as np


def roc_auc(scores_pos: np.ndarray, scores_neg: np.ndarray) -> float:
    """AUC = P(score_pos > score_neg), ties counted half."""
    s = np.concatenate([np.asarray(scores_pos), np.asarray(scores_neg)]).astype(np.float64)
    n_pos, n_neg = len(scores_pos), len(scores_neg)
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty_like(s)
    ranks[order] = np.arange(1, len(s) + 1, dtype=np.float64)
    # average ranks over ties
    sorted_s = s[order]
    uniq, inv, counts = np.unique(sorted_s, return_inverse=True, return_counts=True)
    if len(uniq) != len(s):
        cum = np.cumsum(counts)
        avg = (cum - (counts - 1) / 2.0).astype(np.float64)
        ranks[order] = avg[inv]
    r_pos = ranks[:n_pos].sum()
    u = r_pos - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


def accuracy(logits: np.ndarray, labels: np.ndarray, mask: np.ndarray | None = None) -> float:
    pred = np.asarray(logits).argmax(-1)
    correct = (pred == np.asarray(labels)).astype(np.float64)
    if mask is not None:
        mask = np.asarray(mask, np.float64)
        return float((correct * mask).sum() / np.maximum(mask.sum(), 1.0))
    return float(correct.mean())


def f1_macro(logits: np.ndarray, labels: np.ndarray, num_classes: int,
             mask: np.ndarray | None = None) -> float:
    pred = np.asarray(logits).argmax(-1)
    labels = np.asarray(labels)
    if mask is not None:
        keep = np.asarray(mask, bool)
        pred, labels = pred[keep], labels[keep]
    f1s = []
    for k in range(num_classes):
        tp = float(((pred == k) & (labels == k)).sum())
        fp = float(((pred == k) & (labels != k)).sum())
        fn = float(((pred != k) & (labels == k)).sum())
        denom = 2 * tp + fp + fn
        if denom > 0:
            f1s.append(2 * tp / denom)
    return float(np.mean(f1s)) if f1s else 0.0
