"""Double-buffered host→device prefetch (the overlap half of the
chunked-dispatch loop).

A chunked training run alternates two kinds of work: device compute (one
``lax.scan`` dispatch per chunk) and host batch assembly (numpy planning,
sampling, ``jax.device_put``).  Serializing them wastes whichever is
cheaper; this module overlaps them with the standard two-slot pipeline:
a background thread assembles chunk *i+1* (and starts its host→device
transfer — ``device_put`` in the worker overlaps the copy too) while the
device trains on chunk *i*, handing finished items over a bounded queue.

:class:`HostPrefetcher` is the generic engine;
``models/hgcn_sampled.SampledBatchStream`` (the r04 overlap pipeline this
generalizes) now runs on it, and any runner with host-fed batches can.

Semantics (all load-bearing, mirrored from the stream it replaces):

- **Ordering**: ``next()`` yields ``fn(start)``, ``fn(start+1)``, … in
  order, exactly once each.
- **Bounded look-ahead**: at most ``depth`` finished items are ever
  queued (the worker's put blocks when full), bounding host memory.
- **Failure**: an exception in ``fn`` is re-raised from ``next()`` with
  the real traceback as its cause — a dead silent worker would make
  ``next()`` block forever instead.
- **Shutdown**: ``close()`` (or the context manager) stops the worker,
  drains the queue to unblock a put, and joins the thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable


class HostPrefetcher:
    """Run ``fn(index)`` for index = start, start+1, … in a background
    thread, ``depth`` items ahead of the consumer."""

    def __init__(self, fn: Callable[[int], Any], *, depth: int = 2,
                 start: int = 0):
        self._fn = fn
        self._q: Any = queue.Queue(maxsize=int(depth))
        self._stop = threading.Event()
        self._start = int(start)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        index = self._start
        while not self._stop.is_set():
            try:
                item = self._fn(index)
            except BaseException as e:  # noqa: BLE001 — re-raised in next()
                item = e
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.2)
                    break
                except queue.Full:
                    continue
            if isinstance(item, BaseException):
                return  # consumer re-raises; producing further items
            index += 1  # after a failure would hide it

    def next(self) -> Any:
        """Block until the next item is ready (re-raising worker errors)."""
        item = self._q.get()
        if isinstance(item, BaseException):
            raise RuntimeError(
                f"{type(self).__name__} worker failed") from item
        return item

    def close(self):
        self._stop.set()
        while not self._q.empty():  # unblock a worker stuck on put
            try:
                self._q.get_nowait()
            except Exception:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
