"""Double-buffered host→device prefetch (the overlap half of the
chunked-dispatch loop).

A chunked training run alternates two kinds of work: device compute (one
``lax.scan`` dispatch per chunk) and host batch assembly (numpy planning,
sampling, ``jax.device_put``).  Serializing them wastes whichever is
cheaper; this module overlaps them with the standard two-slot pipeline:
a background thread assembles chunk *i+1* (and starts its host→device
transfer — ``device_put`` in the worker overlaps the copy too) while the
device trains on chunk *i*, handing finished items over a bounded queue.

:class:`HostPrefetcher` is the generic engine;
``models/hgcn_sampled.SampledBatchStream`` (the r04 overlap pipeline this
generalizes) now runs on it, and any runner with host-fed batches can.

Semantics (all load-bearing, mirrored from the stream it replaces):

- **Ordering**: ``next()`` yields ``fn(start)``, ``fn(start+1)``, … in
  order, exactly once each.
- **Bounded look-ahead**: at most ``depth`` finished items are ever
  queued (the worker's put blocks when full), bounding host memory.
- **Failure**: an exception in ``fn`` is re-raised from ``next()`` with
  the real traceback as its cause — a dead silent worker would make
  ``next()`` block forever instead.
- **Shutdown**: ``close()`` (or the context manager) stops the worker,
  drains the queue to unblock a put, and joins the thread.

Telemetry (docs/observability.md): every produced item bumps
``prefetch/produced``, every consumed one ``prefetch/consumed``; a
``next()`` that finds the queue EMPTY — the device out-running the host,
i.e. the overlap failing to hide batch assembly — counts a
``prefetch/stalls`` and accumulates the blocked time into
``prefetch/stall_s`` (also visible as a ``prefetch_wait`` trace span);
the post-get queue depth lands in the ``prefetch/queue_depth`` gauge.
All host-side dict ops on the registry — nothing here touches the
device or adds a sync.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable

from hyperspace_tpu.telemetry import registry as _telem
from hyperspace_tpu.telemetry.trace import span as _span


class HostPrefetcher:
    """Run ``fn(index)`` for index = start, start+1, … in a background
    thread, ``depth`` items ahead of the consumer."""

    def __init__(self, fn: Callable[[int], Any], *, depth: int = 2,
                 start: int = 0):
        self._fn = fn
        self._q: Any = queue.Queue(maxsize=int(depth))
        self._stop = threading.Event()
        self._start = int(start)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        index = self._start
        while not self._stop.is_set():
            try:
                item = self._fn(index)
            except BaseException as e:  # noqa: BLE001 — re-raised in next()
                item = e
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.2)
                    _telem.inc("prefetch/produced")
                    break
                except queue.Full:
                    continue
            if isinstance(item, BaseException):
                return  # consumer re-raises; producing further items
            index += 1  # after a failure would hide it

    def next(self) -> Any:
        """Block until the next item is ready (re-raising worker errors)."""
        from hyperspace_tpu.resilience import faults

        if faults.active():
            # the data.next_batch fault site (docs/resilience.md): an
            # injected IOError/latency lands on the CONSUMER side, where
            # the training loop's failure handling sees it — a worker-
            # thread fault would only reach here wrapped anyway
            faults.hit("data.next_batch")
        if self._q.empty():
            # the device out-ran the host: the wait below is a pipeline
            # stall, not overlap — count it and time it
            _telem.inc("prefetch/stalls")
            t0 = time.perf_counter()
            with _span("prefetch_wait"):
                item = self._q.get()
            _telem.inc("prefetch/stall_s", time.perf_counter() - t0)
        else:
            item = self._q.get()
        _telem.inc("prefetch/consumed")
        _telem.set_gauge("prefetch/queue_depth", self._q.qsize())
        if isinstance(item, BaseException):
            raise RuntimeError(
                f"{type(self).__name__} worker failed") from item
        return item

    def close(self):
        self._stop.set()
        while not self._q.empty():  # unblock a worker stuck on put
            try:
                self._q.get_nowait()
            except queue.Empty:  # raced the worker's last put: done
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShardedHostPrefetcher(HostPrefetcher):
    """Per-host data plane on top of :class:`HostPrefetcher`.

    ``fn(index)`` must build the HOST-IDENTICAL global batch (every
    process computes the same pytree deterministically — the runners'
    existing contract); the worker thread keeps only THIS process's
    leading-axis row range (``multihost.local_batch_rows``), so host
    memory and host→device traffic scale with 1/n_processes, and
    ``next()`` hands back ONE global batch-sharded array per leaf
    (``multihost.assemble_global_batch``).  On a single process this
    degenerates to ``device_put`` with batch sharding — the wiring is
    identical at world size 1 and N.

    The assembly happens on the consumer side because
    ``host_local_array_to_global_array`` may issue a collective —
    every process must reach it in the same order, which the consumer
    loop guarantees and a free-running worker thread would not.
    """

    def __init__(self, fn: Callable[[int], Any], mesh, *, depth: int = 2,
                 start: int = 0):
        from hyperspace_tpu.parallel import multihost as mh

        self._mesh = mesh
        self._assemble = mh.assemble_global_batch

        def local_only(index: int):
            return mh.local_batch_shards(fn(index))

        super().__init__(local_only, depth=depth, start=start)

    def next(self) -> Any:
        return self._assemble(super().next(), self._mesh)
