"""WordNet-style hierarchy data: transitive closure + negative sampling.

Reference workload 1 (BASELINE.json configs[0]): Poincaré embeddings on the
WordNet noun hypernymy closure (Nickel & Kiela 2017).  This environment has
no network access and no bundled WordNet dump, so the loader accepts any
edge list in TSV form (``child<TAB>parent`` per line, the format the
published closure files use) and can also synthesize benchmark trees of a
chosen size.  The transitive closure is computed by the native C++ helper
(``hyperspace_tpu.data.native``) when its extension has been built, else by
a pure-Python DFS fallback.

Negative sampling is done *on device* inside the jitted train step with
``jax.random`` — the host never touches the per-step batch (SURVEY.md §3.1:
host→device once per batch, or none when the closure fits on device).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class ClosureDataset:
    """A hierarchy as (child, ancestor) pairs over ``num_nodes`` vocab ids."""

    pairs: np.ndarray  # [P, 2] int32 (u, v): v is an ancestor of u
    num_nodes: int
    names: list[str] | None = None

    @property
    def num_pairs(self) -> int:
        return int(self.pairs.shape[0])

    def adjacency_set(self) -> set[tuple[int, int]]:
        return {(int(u), int(v)) for u, v in self.pairs}


def load_edges_tsv(path: str) -> tuple[np.ndarray, list[str]]:
    """Read ``child<TAB>parent`` lines; returns (edges [E,2] int32, names)."""
    ids: dict[str, int] = {}
    edges = []
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 2 or parts[0].startswith("#"):
                continue
            u, v = parts[0], parts[1]
            for t in (u, v):
                if t not in ids:
                    ids[t] = len(ids)
            edges.append((ids[u], ids[v]))
    names = [None] * len(ids)
    for t, i in ids.items():
        names[i] = t
    return np.asarray(edges, np.int32), names


def transitive_closure(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """All (node, ancestor) pairs reachable through the parent relation.

    Uses the native C++ closure (hyperspace_tpu.data.native) when the
    extension is built; otherwise a pure-Python DFS fallback.
    """
    try:
        from hyperspace_tpu.data import native

        return native.transitive_closure(edges, num_nodes)
    except (ImportError, OSError):  # no toolchain / build failed
        return _closure_numpy(edges, num_nodes)


def _closure_numpy(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    parents: list[list[int]] = [[] for _ in range(num_nodes)]
    for u, v in edges:
        parents[int(u)].append(int(v))
    out = []
    for start in range(num_nodes):
        seen: set[int] = set()
        stack = list(parents[start])
        while stack:
            p = stack.pop()
            if p in seen:
                continue
            seen.add(p)
            stack.extend(parents[p])
        out.extend((start, a) for a in seen)
    if not out:
        return np.zeros((0, 2), np.int32)
    return np.asarray(out, np.int32)


def load_closure_tsv(path: str, already_closed: bool = True) -> ClosureDataset:
    edges, names = load_edges_tsv(path)
    n = len(names)
    pairs = edges if already_closed else transitive_closure(edges, n)
    return ClosureDataset(pairs=pairs, num_nodes=n, names=names)


def synthetic_tree(depth: int, branching: int, seed: int = 0) -> ClosureDataset:
    """A complete ``branching``-ary tree of the given depth, closed.

    Node 0 is the root.  Used by tests (SURVEY.md §4.5: recover a tiny tree
    to MAP=1.0) and by the Poincaré-embedding benchmark when no WordNet TSV
    is available.
    """
    del seed
    edges = []
    level = [0]
    next_id = 1
    for _ in range(depth):
        nxt = []
        for p in level:
            for _ in range(branching):
                edges.append((next_id, p))
                nxt.append(next_id)
                next_id += 1
        level = nxt
    edges = np.asarray(edges, np.int32)
    pairs = transitive_closure(edges, next_id)
    return ClosureDataset(pairs=pairs, num_nodes=next_id, names=None)
