"""Text-classification data (reference workload 3: HyboNet text-clf).

No network access in this environment, so the loader reads a simple
``label<TAB>text`` TSV when present (whitespace tokenization, vocab built
from the training split) and otherwise synthesizes a classification corpus
with class-dependent token distributions — enough signal to verify the
HyboNet encoder learns (SURVEY.md §4.7 integration-test strategy).

Sequences are padded to ``max_len`` with id 0 (PAD) and carried with a mask
— static shapes for XLA, like every other loader here.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

PAD_ID = 0


@dataclasses.dataclass
class TextDataset:
    tokens: np.ndarray  # [N, L] int32, 0 = pad
    mask: np.ndarray  # [N, L] bool
    labels: np.ndarray  # [N] int32
    vocab_size: int
    num_classes: int

    def split(self, train_frac: float = 0.8, seed: int = 0):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self.labels))
        n_tr = int(len(perm) * train_frac)
        tr, te = perm[:n_tr], perm[n_tr:]
        pick = lambda idx: TextDataset(
            self.tokens[idx], self.mask[idx], self.labels[idx],
            self.vocab_size, self.num_classes)
        return pick(tr), pick(te)


def _pad(seqs: list[list[int]], max_len: int):
    n = len(seqs)
    toks = np.zeros((n, max_len), np.int32)
    mask = np.zeros((n, max_len), bool)
    for i, s in enumerate(seqs):
        s = s[:max_len]
        toks[i, : len(s)] = s
        mask[i, : len(s)] = True
    return toks, mask


def load_tsv(path: str, max_len: int = 64, max_vocab: int = 30000) -> TextDataset:
    """``label<TAB>text`` lines; builds a frequency-capped vocab (1 = UNK)."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t", 1)
            if len(parts) == 2:
                rows.append((parts[0], parts[1].lower().split()))
    labels_map: dict[str, int] = {}
    freq: dict[str, int] = {}
    for lab, toks in rows:
        labels_map.setdefault(lab, len(labels_map))
        for t in toks:
            freq[t] = freq.get(t, 0) + 1
    vocab = {t: i + 2 for i, (t, _) in enumerate(
        sorted(freq.items(), key=lambda kv: -kv[1])[: max_vocab - 2])}
    seqs = [[vocab.get(t, 1) for t in toks] for _, toks in rows]
    toks, mask = _pad(seqs, max_len)
    labels = np.asarray([labels_map[lab] for lab, _ in rows], np.int32)
    return TextDataset(toks, mask, labels, len(vocab) + 2, len(labels_map))


def synthetic_text(
    num_samples: int = 2048,
    vocab_size: int = 512,
    num_classes: int = 4,
    max_len: int = 32,
    min_len: int = 8,
    class_sharpness: float = 3.0,
    seed: int = 0,
) -> TextDataset:
    """Class-dependent unigram corpora (ids 0/1 reserved for PAD/UNK)."""
    rng = np.random.default_rng(seed)
    usable = vocab_size - 2
    logits = class_sharpness * rng.normal(size=(num_classes, usable))
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    labels = rng.integers(0, num_classes, num_samples).astype(np.int32)
    seqs = []
    for y in labels:
        ln = int(rng.integers(min_len, max_len + 1))
        seqs.append(list(rng.choice(usable, size=ln, p=probs[y]) + 2))
    toks, mask = _pad(seqs, max_len)
    return TextDataset(toks, mask, labels, vocab_size, num_classes)


def load_text(name: str, root: str | None = None, **synth_kw) -> tuple[TextDataset, str]:
    if root is not None:
        path = os.path.join(root, f"{name}.tsv")
        if os.path.exists(path):
            return load_tsv(path), "disk"
    return synthetic_text(**synth_kw), "synthetic"
