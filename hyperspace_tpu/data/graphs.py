"""Graph datasets for HGCN: Cora / ogbn-arxiv loaders + synthetic fallbacks.

Reference workload 2 (BASELINE.json configs[1]): hyperbolic GCN on
Cora / ogbn-arxiv in the Lorentz model — the north-star benchmark
(SURVEY.md §0, §3.2).

TPU constraint (SURVEY.md §7 hard-part #3): XLA wants static shapes, so the
edge list is **padded to a bucket size** and carried with a boolean mask;
aggregation is masked ``segment_sum`` over receivers, never ragged ops.

This environment has no network access, so the loaders read standard
on-disk formats when present (Planetoid ``cora.content``/``cora.cites``;
OGB's extracted csv layout) and otherwise synthesize structurally similar
graphs: a noisy hierarchy (trees embed well in hyperbolic space, so link
prediction ROC-AUC is a meaningful quality signal — the same reason the
reference's workloads are hierarchy-shaped) with community-correlated
features for node classification.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class Graph:
    """A static-shape graph: padded edge list + masks.

    ``senders``/``receivers`` hold one direction per stored edge; callers
    that need symmetric message passing should build the graph through
    :func:`prepare` which symmetrizes and adds self-loops before padding.
    """

    x: np.ndarray  # [N, F] float32 node features
    senders: np.ndarray  # [E_pad] int32
    receivers: np.ndarray  # [E_pad] int32, sorted ascending (see prepare)
    edge_mask: np.ndarray  # [E_pad] bool (False = padding)
    num_nodes: int
    rev_perm: np.ndarray | None = None  # [E_pad] int32 edge -> reverse edge
    deg: np.ndarray | None = None  # [N] float32 masked in-degree (static)
    csr_plan: tuple | None = None  # kernels.segment.CsrPlan work items
    cluster_split: Any | None = None  # kernels.cluster.ClusterSplit (mean agg)
    labels: np.ndarray | None = None  # [N] int32
    num_classes: int = 0
    train_mask: np.ndarray | None = None  # [N] bool (node tasks)
    val_mask: np.ndarray | None = None
    test_mask: np.ndarray | None = None

    @property
    def num_edges(self) -> int:
        return int(self.edge_mask.sum())


class DeviceGraph(NamedTuple):
    """Device-resident graph arrays, one pytree leaf per field.

    The single argument models/layers take for message passing; built once
    per graph with :func:`to_device`.  Optional fields are ``None`` when
    the graph was not built by :func:`prepare` (consumers then fall back
    to plain masked segment ops).
    """

    x: "jax.Array"                      # [N, F]
    senders: "jax.Array"                # [E] int32
    receivers: "jax.Array"              # [E] int32 sorted
    edge_mask: "jax.Array"              # [E] bool
    num_nodes: int                      # static (python int)
    rev_perm: Optional["jax.Array"] = None   # [E] int32 involution
    deg: Optional["jax.Array"] = None        # [N] f32 masked in-degree
    plan: Optional[tuple] = None             # 3 × [T] int32 CSR work items
    cluster: Any = None                      # nn.scatter.ClusterAgg (mean agg)


# num_nodes must stay a static (hashable) field across jit boundaries, so
# DeviceGraph is registered with num_nodes as auxiliary pytree data.
def _dg_flatten(g: DeviceGraph):
    return (g.x, g.senders, g.receivers, g.edge_mask, g.rev_perm, g.deg,
            g.plan, g.cluster), g.num_nodes


def _dg_unflatten(num_nodes, leaves):
    x, s, r, m, rp, deg, plan, cluster = leaves
    return DeviceGraph(x, s, r, m, num_nodes, rp, deg, plan, cluster)


jax.tree_util.register_pytree_node(DeviceGraph, _dg_flatten, _dg_unflatten)


def to_device(g: Graph) -> DeviceGraph:
    """Put a host :class:`Graph` on device as a :class:`DeviceGraph`."""
    cluster = None
    if g.cluster_split is not None:
        from hyperspace_tpu.nn.scatter import ClusterAgg

        cluster = ClusterAgg.from_host(g.cluster_split)
    return DeviceGraph(
        x=jnp.asarray(g.x),
        senders=jnp.asarray(g.senders),
        receivers=jnp.asarray(g.receivers),
        edge_mask=jnp.asarray(g.edge_mask),
        num_nodes=g.num_nodes,
        rev_perm=None if g.rev_perm is None else jnp.asarray(g.rev_perm),
        deg=None if g.deg is None else jnp.asarray(g.deg),
        plan=None if g.csr_plan is None
        else tuple(jnp.asarray(a) for a in g.csr_plan),
        cluster=cluster,
    )


@dataclasses.dataclass
class LinkSplit:
    """Edge split for link prediction (SURVEY.md §3.2 LP head).

    ``graph`` contains only the *training* edges (message passing must not
    see held-out edges).  val/test arrays are [K, 2] (u, v) pairs.
    """

    graph: Graph
    train_pos: np.ndarray
    val_pos: np.ndarray
    val_neg: np.ndarray
    test_pos: np.ndarray
    test_neg: np.ndarray


def _pad_to(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _prepare_edges_numpy(edges, num_nodes, *, symmetrize=True,
                         self_loops=True, pad_multiple=1024):
    """Numpy edge-layout pipeline: the fallback for :func:`prepare` and
    the parity oracle for ``native.prepare_edges`` (tests/data).

    Returns (senders, receivers, mask, rev_perm, deg); ``rev_perm`` is
    None unless ``symmetrize``.
    """
    e = np.asarray(edges, np.int64)
    if symmetrize and len(e):
        e = np.concatenate([e, e[:, ::-1]], axis=0)
    if self_loops:
        loops = np.stack([np.arange(num_nodes)] * 2, axis=1)
        e = np.concatenate([e, loops], axis=0) if len(e) else loops
    # dedupe + sort by (receiver, sender) via flat receiver-major keys
    key = e[:, 1] * num_nodes + e[:, 0]
    e = e[np.unique(key, return_index=True)[1]]
    e_pad = _pad_to(max(len(e), 1), pad_multiple)
    senders = np.full(e_pad, num_nodes - 1, np.int32)
    receivers = np.full(e_pad, num_nodes - 1, np.int32)
    mask = np.zeros(e_pad, bool)
    senders[: len(e)] = e[:, 0]
    receivers[: len(e)] = e[:, 1]
    mask[: len(e)] = True

    rev_perm = None
    if symmetrize:
        # reverse of (s, r) has key s·N + r; keys are sorted, so
        # searchsorted gives its index.  Padding maps to itself.
        keys_sorted = e[:, 1] * num_nodes + e[:, 0]
        rev_perm = np.arange(e_pad, dtype=np.int32)
        rev_perm[: len(e)] = np.searchsorted(
            keys_sorted, e[:, 0] * num_nodes + e[:, 1]).astype(np.int32)
    deg = np.bincount(receivers[mask], minlength=num_nodes).astype(np.float32)
    return senders, receivers, mask, rev_perm, deg


def _check_edge_range(edges, num_nodes: int) -> None:
    """Raise IndexError on out-of-range ids BEFORE any native path runs
    (the C++ pipelines do no bounds checks — a bad id would silently
    corrupt memory or segfault instead of raising)."""
    e = np.asarray(edges)
    if len(e) and (e.min() < 0 or e.max() >= num_nodes):
        raise IndexError(
            f"edge ids out of range [0, {num_nodes}): min {e.min()}, "
            f"max {e.max()}")


# raw-edge-count gate for cache="auto" (data/prep_cache.py): below this
# the host prep is cheaper than hashing + disk IO, and unit-test graphs
# must never touch the on-disk cache
_CACHE_AUTO_MIN_EDGES = 200_000


def cluster_min_pair_for(use_att: bool) -> int:
    """The mode-dependent cluster-pair density threshold — ONE home for
    the r05 sweep result (docs/benchmarks.md "Per-mode cluster
    threshold"): mean aggregation wins at 256, attention at 128 (the
    in-tile attention kernels save enough [E]-stream per clustered edge
    that sparser pairs still pay).  Re-sweeps update this function only.
    """
    return 128 if use_att else 256


def prepare(
    edges: np.ndarray,
    num_nodes: int,
    x: np.ndarray,
    *,
    symmetrize: bool = True,
    self_loops: bool = True,
    pad_multiple: int = 1024,
    cluster: str | bool = "auto",
    cluster_min_pair: int = 256,
    cache: Any = "auto",
    **node_fields,
) -> Graph:
    """Symmetrize, add self-loops, dedupe, sort by receiver, pad.

    TPU layout decisions (SURVEY.md §2 "padding/bucketing needed on TPU"
    and §7 hard-part #3):

    - Edges are **sorted by (receiver, sender)** so every aggregation
      scatter runs XLA's sorted fast path (~2.3× at arxiv scale).
    - ``rev_perm`` maps each edge to its reverse (self-loops and padding
      map to themselves), letting the aggregation VJP scatter sorted too
      (see nn/scatter.py).  Requires ``symmetrize=True``; otherwise left
      ``None`` and consumers fall back to plain segment ops.
    - Padding edges are (N−1, N−1) with ``edge_mask`` False — the max key
      keeps the receiver order sorted; weight 0 keeps them inert.
    - ``deg`` (masked in-degree) and ``csr_plan`` (the block-CSR work-item
      schedule for :func:`hyperspace_tpu.kernels.segment.csr_segment_sum`)
      are static per graph, so they are computed here once instead of per
      training step.
    - The whole edge layout (everything above plus the cluster split) is
      a pure function of (edges, num_nodes, knobs), so it is served from
      the persistent :mod:`hyperspace_tpu.data.prep_cache` when ``cache``
      allows — ``"auto"`` caches big graphs only; pass ``True``/a
      ``PrepCache`` to force, ``False`` to disable.  ``x`` and the node
      fields ride outside the cache (they don't shape the edge layout).
    """
    _check_edge_range(edges, num_nodes)
    from hyperspace_tpu.data import prep_cache

    e_arr = np.asarray(edges)
    pc = prep_cache.resolve(
        cache, auto_ok=len(e_arr) >= _CACHE_AUTO_MIN_EDGES)
    build = lambda: _build_edge_layout(
        e_arr, num_nodes, symmetrize=symmetrize, self_loops=self_loops,
        pad_multiple=pad_multiple, cluster=cluster,
        cluster_min_pair=cluster_min_pair)
    if pc is not None:
        layout = pc.get_or_build(
            "edge-layout",
            (e_arr.astype(np.int64, copy=False), num_nodes, symmetrize,
             self_loops, pad_multiple, str(cluster), cluster_min_pair),
            build)
    else:
        layout = build()

    return Graph(
        x=np.asarray(x, np.float32),
        num_nodes=num_nodes,
        **layout,
        **node_fields,
    )


def _build_edge_layout(edges, num_nodes, *, symmetrize, self_loops,
                       pad_multiple, cluster, cluster_min_pair) -> dict:
    """The cacheable core of :func:`prepare`: every edge-derived artifact
    as a dict of Graph field values (no x/labels/masks)."""
    senders = receivers = mask = rev_perm = deg = None
    try:  # native C++ pipeline; _prepare_edges_numpy is the oracle
        from hyperspace_tpu.data import native

        senders, receivers, mask, rev_perm, deg = native.prepare_edges(
            np.asarray(edges, np.int32), num_nodes, symmetrize=symmetrize,
            self_loops=self_loops, pad_multiple=pad_multiple)
        if not symmetrize:
            rev_perm = None
    except (ImportError, OSError):
        pass
    if senders is None:
        senders, receivers, mask, rev_perm, deg = _prepare_edges_numpy(
            edges, num_nodes, symmetrize=symmetrize, self_loops=self_loops,
            pad_multiple=pad_multiple)

    from hyperspace_tpu.kernels.segment import build_csr_plan

    # cluster-pair split (kernels/cluster.py): avoids the [E, F] message
    # round-trip for block-dense edges.  "auto" builds it only at scales
    # where the aggregation is actually HBM-bound (the one-time host sort
    # is wasted on toy graphs, and small graphs fit the plain path fine).
    # ``cluster_min_pair``: the (rb, sb)-pair density threshold.  The
    # r05 same-session sweep (docs/benchmarks.md) found the best value
    # is MODE-dependent: 256 for mean aggregation (0.1288 vs 0.1314 s
    # at 128) but 128 for attention (0.2771 vs 0.2898 s) — the in-tile
    # attention kernels save enough [E]-stream per clustered edge that
    # sparser pairs still pay; callers that know attention will run
    # pass 128 (cli.train, run_hgcn_bench use_att).
    split = None
    n_real = int(mask.sum())
    if cluster is True or (cluster == "auto" and n_real >= 200_000):
        if symmetrize:  # the involution backward needs a symmetric set
            from hyperspace_tpu.kernels.cluster import build_cluster_split

            split = build_cluster_split(senders, receivers, mask, deg,
                                        num_nodes, rev_perm=rev_perm,
                                        min_pair_edges=cluster_min_pair)

    return dict(
        senders=senders,
        receivers=receivers,
        edge_mask=mask,
        rev_perm=rev_perm,
        deg=deg,
        csr_plan=tuple(build_csr_plan(receivers, num_nodes)),
        cluster_split=split,
    )


# --- link-prediction split ----------------------------------------------------


def split_edges(
    edges: np.ndarray,
    num_nodes: int,
    x: np.ndarray,
    *,
    val_frac: float = 0.05,
    test_frac: float = 0.10,
    seed: int = 0,
    pad_multiple: int = 1024,
    cluster_min_pair: int = 256,
    cache: Any = "auto",
    **node_fields,
) -> LinkSplit:
    """Hold out edges for LP eval; message passing uses only train edges.

    Negatives are uniform non-edges, the Chami et al. 2019 protocol whose
    ROC-AUC is the [B] quality target.  The host split (canonicalized
    permutation + rejection-sampled negatives) is deterministic in
    (edges, num_nodes, fracs, seed), so it caches persistently alongside
    the prepared graph's edge layout (``cache`` — see :func:`prepare`).
    """
    e = np.asarray(edges, np.int64)

    def build() -> dict:
        # the WHOLE host split lives inside the cached builder — the
        # O(E log E) canonicalize/sort/dedup/permutation is most of the
        # cost at arxiv scale, so a cache hit must skip it too, not just
        # the negative sampling
        rng = np.random.default_rng(seed)
        # undirected canonical form for splitting
        canon = np.sort(e, axis=1)
        canon = canon[np.unique(canon[:, 0] * num_nodes + canon[:, 1],
                                return_index=True)[1]]
        perm = rng.permutation(len(canon))
        n_val = int(len(canon) * val_frac)
        n_test = int(len(canon) * test_frac)
        val_pos = canon[perm[:n_val]]
        test_pos = canon[perm[n_val : n_val + n_test]]
        train_pos = canon[perm[n_val + n_test :]]

        def sample_neg(k: int) -> np.ndarray:
            try:  # native rejection sampler (arxiv-scale edge sets)
                from hyperspace_tpu.data import native

                neg = native.sample_negative_edges(
                    canon, num_nodes, k, seed=int(rng.integers(2**31)))
                if len(neg) == k:
                    return neg.astype(np.int64)
            except (ImportError, OSError):
                pass
            edge_set = {(int(u), int(v)) for u, v in canon}
            out = []
            while len(out) < k:
                cand = rng.integers(0, num_nodes,
                                    size=(2 * (k - len(out)) + 16, 2))
                for u, v in cand:
                    if u == v:
                        continue
                    a, b = (int(u), int(v)) if u < v else (int(v), int(u))
                    if (a, b) in edge_set:
                        continue
                    out.append((a, b))
                    if len(out) == k:
                        break
            return np.asarray(out, np.int64)

        return dict(
            train_pos=train_pos.astype(np.int32),
            val_pos=val_pos.astype(np.int32),
            val_neg=sample_neg(len(val_pos)).astype(np.int32),
            test_pos=test_pos.astype(np.int32),
            test_neg=sample_neg(len(test_pos)).astype(np.int32),
        )

    from hyperspace_tpu.data import prep_cache

    pc = prep_cache.resolve(cache, auto_ok=len(e) >= _CACHE_AUTO_MIN_EDGES)
    if pc is not None:
        arrs = pc.get_or_build(
            "lp-split", (e, num_nodes, val_frac, test_frac, seed), build)
    else:
        arrs = build()
    g = prepare(
        arrs["train_pos"], num_nodes, x, pad_multiple=pad_multiple,
        cluster_min_pair=cluster_min_pair, cache=cache, **node_fields
    )
    return LinkSplit(graph=g, **arrs)


# --- on-disk loaders ----------------------------------------------------------


def load_cora(root: str):
    """Planetoid raw format: ``cora.content`` + ``cora.cites``.

    Returns (edges [E,2], x [N,F], labels [N], num_classes).
    """
    content = os.path.join(root, "cora.content")
    cites = os.path.join(root, "cora.cites")
    ids, feats, labels, label_ids = {}, [], [], {}
    with open(content) as f:
        for line in f:
            parts = line.strip().split()
            ids[parts[0]] = len(ids)
            feats.append([float(t) for t in parts[1:-1]])
            lab = parts[-1]
            label_ids.setdefault(lab, len(label_ids))
            labels.append(label_ids[lab])
    edges = []
    with open(cites) as f:
        for line in f:
            a, b = line.strip().split()
            if a in ids and b in ids:
                edges.append((ids[a], ids[b]))
    return (
        np.asarray(edges, np.int64),
        np.asarray(feats, np.float32),
        np.asarray(labels, np.int32),
        len(label_ids),
    )


def _read_csv(path: str, dtype):
    """Fast csv matrix read: pandas C engine when available (an order of
    magnitude faster at arxiv scale — node-feat.csv is ~21.7 M floats),
    np.loadtxt as the no-pandas fallback."""
    try:
        import pandas as pd

        return pd.read_csv(path, header=None, dtype=dtype).to_numpy()
    except ImportError:
        return np.loadtxt(path, delimiter=",", dtype=dtype)


def load_ogbn_arxiv(root: str):
    """OGB extracted-csv layout (``raw/edge.csv``, ``raw/node-feat.csv``...)."""
    raw = os.path.join(root, "raw")
    edges = _read_csv(os.path.join(raw, "edge.csv"), np.int64)
    x = np.ascontiguousarray(
        _read_csv(os.path.join(raw, "node-feat.csv"), np.float32))
    labels = _read_csv(os.path.join(raw, "node-label.csv"), np.int64)
    return edges, x, labels.astype(np.int32).reshape(-1), int(labels.max()) + 1


def write_ogb_csv_layout(root: str, edges: np.ndarray, x: np.ndarray,
                         labels: np.ndarray) -> None:
    """Write a graph to the OGB extracted-csv layout ``load_ogbn_arxiv``
    reads (``raw/{edge,node-feat,node-label}.csv``) — the disk end of the
    disk → load → prepare → train pipeline."""
    raw = os.path.join(root, "raw")
    os.makedirs(raw, exist_ok=True)

    def _write(path, a, fmt):
        try:  # pandas C writer: ~10x np.savetxt on the 21.7M-float feat
            import pandas as pd

            pd.DataFrame(a).to_csv(path, header=False, index=False,
                                   float_format="%.6g")
        except ImportError:
            np.savetxt(path, a, fmt=fmt, delimiter=",")

    _write(os.path.join(raw, "edge.csv"), np.asarray(edges, np.int64), "%d")
    _write(os.path.join(raw, "node-feat.csv"), np.asarray(x, np.float32),
           "%.6g")
    _write(os.path.join(raw, "node-label.csv"),
           np.asarray(labels, np.int64).reshape(-1, 1), "%d")


# --- synthetic fallbacks ------------------------------------------------------


def synthetic_hierarchy(
    num_nodes: int = 1024,
    branching: int = 3,
    feat_dim: int = 32,
    ancestor_hops: int = 3,
    extra_edge_frac: float = 0.02,
    num_classes: int = 4,
    seed: int = 0,
):
    """A noisy hierarchy with community-correlated features.

    Structure: a ``branching``-ary tree over all nodes, **plus ancestor
    edges up to ``ancestor_hops`` levels** (a truncated transitive closure)
    and a few random cross edges.  The ancestor edges make the graph
    structurally redundant: every held-out link has parallel 2-hop paths
    (child—grandparent—parent), so link prediction from message passing is
    well-posed — a pure tree would disconnect under edge removal and cap
    ROC-AUC near chance.  Hierarchies have strong negative curvature, so
    hyperbolic models fit them well — the signal the integration tests
    assert (SURVEY.md §4.7).

    Class = top-level subtree; features = class prototype + noise + a depth
    coordinate.  Returns (edges [E,2], x [N,F], labels [N], num_classes).
    """
    rng = np.random.default_rng(seed)
    parent = np.zeros(num_nodes, np.int64)
    parent[1:] = (np.arange(1, num_nodes) - 1) // branching
    edges = []
    for i in range(1, num_nodes):
        anc = i
        for _ in range(max(1, ancestor_hops)):
            anc = int(parent[anc])
            edges.append((i, anc))
            if anc == 0:
                break
    n_extra = int(num_nodes * extra_edge_frac)
    for _ in range(n_extra):
        u, v = rng.integers(0, num_nodes, 2)
        if u != v:
            edges.append((int(u), int(v)))
    edges = np.asarray(edges, np.int64)

    # class of a node = which depth-1 subtree it falls under
    depth = np.zeros(num_nodes, np.int64)
    top = np.zeros(num_nodes, np.int64)
    for i in range(1, num_nodes):
        depth[i] = depth[parent[i]] + 1
        top[i] = i if depth[i] == 1 else top[parent[i]]
    labels = (top % num_classes).astype(np.int32)
    labels[0] = 0

    protos = rng.normal(size=(num_classes, feat_dim)).astype(np.float32)
    x = protos[labels] + 0.4 * rng.normal(size=(num_nodes, feat_dim)).astype(np.float32)
    x[:, 0] = depth / max(depth.max(), 1)
    return edges, x, labels, num_classes


def community_power_law_graph(
    num_nodes: int = 169_343,
    num_edges: int = 1_166_243,
    num_classes: int = 40,
    feat_dim: int = 128,
    gamma: float = 2.6,
    p_in: float = 0.72,
    p_sub: float = 0.55,
    sub_size: int = 400,
    triadic_frac: float = 0.15,
    seed: int = 0,
):
    """Community-structured power-law graph at citation-network statistics.

    The uniform-random edge majority of :func:`synthetic_hierarchy` is
    *unclusterable by construction* — adversarial to the BFS-locality /
    cluster-pair levers real citation graphs reward (VERDICT r3 #3).
    This generator produces the structure those levers were built for,
    with ogbn-arxiv-like shape statistics:

    - **degree-corrected SBM**: node degrees follow a truncated power law
      (exponent ``gamma``, arxiv's in-degree tail fits ~2.5–3); both edge
      endpoints are degree-weighted, so hubs emerge.
    - **communities**: ``num_classes`` groups with power-law sizes; a
      ``p_in`` fraction of edges stay inside the sender's community
      (arxiv's label assortativity ~0.65–0.8 depending on measure).
      Class label = community; features = community prototype + noise
      (same recipe as :func:`synthetic_hierarchy`).
    - **hierarchical sub-communities**: citation topics cluster down to
      research-group scale, not just field scale — within a community,
      a ``p_sub`` fraction of its internal edges stay inside the
      sender's ~``sub_size``-node sub-community.  This is the level the
      BFS locality reorder converts into (receiver-block × sender-block)
      density for the cluster-pair kernel.
    - **triadic closure**: ``triadic_frac`` of edges connect two
      neighbors of a shared node, lifting the clustering coefficient
      from the SBM's near-zero toward citation-graph levels.

    Returns (edges [E, 2] directed, x [N, F], labels [N], num_classes).
    """
    rng = np.random.default_rng(seed)
    # truncated power-law degree propensities (inverse-transform Pareto)
    u = rng.random(num_nodes)
    prop = np.minimum(u ** (-1.0 / (gamma - 1.0)), num_nodes ** 0.5)
    prop /= prop.sum()
    # power-law community sizes via Dirichlet over a decaying base measure
    base = (1.0 / np.arange(1, num_classes + 1)) ** 0.8
    sizes = rng.dirichlet(base * num_classes)
    comm = rng.choice(num_classes, size=num_nodes, p=sizes)

    # sub-communities: chunk each community's member list into
    # ~sub_size-node groups (globally-unique sub ids)
    sub = np.zeros(num_nodes, np.int64)
    next_sub = 0
    for c in range(num_classes):
        members = np.flatnonzero(comm == c)
        n_sub = max(1, len(members) // sub_size)
        sub[members] = next_sub + rng.integers(0, n_sub, len(members))
        next_sub += n_sub

    n_base = int(num_edges * (1.0 - triadic_frac))
    senders = rng.choice(num_nodes, size=n_base, p=prop)
    receivers = np.empty(n_base, np.int64)
    r_scope = rng.random(n_base)
    in_comm = r_scope < p_in
    in_sub = r_scope < p_in * p_sub
    out_idx = np.flatnonzero(~in_comm)
    receivers[out_idx] = rng.choice(num_nodes, size=len(out_idx), p=prop)

    def _fill_grouped(group_of, take_mask):
        """Degree-weighted receiver draw within the sender's group."""
        take = np.flatnonzero(take_mask)
        if len(take) == 0:
            return
        gids = group_of[senders[take]]
        order = np.argsort(gids, kind="stable")
        take = take[order]
        gids = gids[order]
        starts = np.flatnonzero(np.r_[True, gids[1:] != gids[:-1]])
        ends = np.r_[starts[1:], len(gids)]
        for st, en in zip(starts, ends):
            members = np.flatnonzero(group_of == gids[st])
            pc = prop[members] / prop[members].sum()
            receivers[take[st:en]] = members[
                rng.choice(len(members), size=en - st, p=pc)]

    _fill_grouped(sub, in_sub)
    _fill_grouped(comm, in_comm & ~in_sub)
    edges = np.stack([senders, receivers], axis=1)
    edges = edges[edges[:, 0] != edges[:, 1]]

    # triadic closure: connect two neighbors of a shared pivot
    n_tri = num_edges - len(edges)
    if n_tri > 0:
        # close triangles by pairing receivers of edges sharing a sender:
        # sort by sender, draw pivot edges, connect each pivot's receiver
        # to its sender-sorted neighbor's receiver
        pivots = rng.choice(len(edges), size=n_tri)
        bysend = np.argsort(edges[:, 0], kind="stable")
        a = edges[bysend[pivots], :]
        b = edges[bysend[np.minimum(pivots + 1, len(edges) - 1)], :]
        share = a[:, 0] == b[:, 0]
        tri = np.stack([a[share, 1], b[share, 1]], axis=1)
        tri = tri[tri[:, 0] != tri[:, 1]]
        edges = np.concatenate([edges, tri], axis=0)[:num_edges]

    protos = rng.normal(size=(num_classes, feat_dim)).astype(np.float32)
    labels = comm.astype(np.int32)
    x = protos[labels] + 0.4 * rng.normal(
        size=(num_nodes, feat_dim)).astype(np.float32)
    return edges.astype(np.int64), x, labels, num_classes


def node_split_masks(num_nodes: int, train_frac=0.6, val_frac=0.2, seed: int = 0):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_nodes)
    n_tr = int(num_nodes * train_frac)
    n_va = int(num_nodes * val_frac)
    tr = np.zeros(num_nodes, bool)
    va = np.zeros(num_nodes, bool)
    te = np.zeros(num_nodes, bool)
    tr[perm[:n_tr]] = True
    va[perm[n_tr : n_tr + n_va]] = True
    te[perm[n_tr + n_va :]] = True
    return tr, va, te


def load_graph(name: str, root: str | None = None, **synth_kw):
    """Dispatch: real dataset if its files exist under ``root``, else synthetic.

    Returns (edges, x, labels, num_classes, source) where source is
    "disk" or "synthetic".
    """
    if root is not None:
        if name == "cora" and os.path.exists(os.path.join(root, "cora.content")):
            return (*load_cora(root), "disk")
        if name == "ogbn-arxiv" and os.path.exists(
            os.path.join(root, "raw", "edge.csv")
        ):
            return (*load_ogbn_arxiv(root), "disk")
    defaults = {"cora": dict(num_nodes=2048, feat_dim=64, num_classes=7),
                "ogbn-arxiv": dict(num_nodes=16384, feat_dim=128, num_classes=40)}
    kw = {**defaults.get(name, {}), **synth_kw}
    return (*synthetic_hierarchy(**kw), "synthetic")


# --- locality reordering ------------------------------------------------------


def locality_order(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """BFS relabeling that clusters neighborhoods into contiguous id
    ranges.

    Returns ``order`` with ``order[rank] = old_id``: BFS from the
    highest-degree node of each component (high-degree seeds keep hub
    neighborhoods contiguous).  Real citation graphs arrive with
    essentially random ids; after this relabeling their community
    structure becomes (receiver-block × sender-block) locality, which is
    what the cluster-pair SpMM kernel (kernels/cluster.py) converts into
    VMEM-tile reuse.  The relabeling is a graph isomorphism — quality
    metrics are unaffected, only the memory layout changes.

    Dispatches to the native C++ BFS (``data/_native/localorder.cc``,
    47× at arxiv scale: 1.14 s → 24 ms) when the toolchain is
    available; the pure-Python deque walk below is the fallback and the
    parity oracle.
    """
    e = np.asarray(edges)
    # validate HERE so native and fallback paths fail identically (the
    # C++ walk would OOB-write silently; the python walk would wrap
    # negative ids)
    _check_edge_range(e, num_nodes)
    try:
        from hyperspace_tpu.data import native

        return native.locality_order(np.asarray(e, np.int32), num_nodes)
    except (ImportError, OSError):
        return _locality_order_python(e, num_nodes)


def _locality_order_python(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """Pure-Python BFS fallback and parity oracle for locality_order."""
    from collections import deque

    e = np.asarray(edges, np.int64)
    e = np.concatenate([e, e[:, ::-1]], axis=0)
    e = e[np.argsort(e[:, 0], kind="stable")]
    indptr = np.searchsorted(e[:, 0], np.arange(num_nodes + 1))
    nbr = e[:, 1]
    deg = np.diff(indptr)
    seeds = np.argsort(-deg, kind="stable")
    visited = np.zeros(num_nodes, bool)
    out = np.empty(num_nodes, np.int64)
    pos = 0
    si = 0
    q = deque()
    while pos < num_nodes:
        while si < num_nodes and visited[seeds[si]]:
            si += 1
        root = seeds[si]
        visited[root] = True
        q.append(root)
        while q:
            u = q.popleft()
            out[pos] = u
            pos += 1
            for v in nbr[indptr[u] : indptr[u + 1]]:
                if not visited[v]:
                    visited[v] = True
                    q.append(v)
    return out


def _lpa_sweeps(snd: np.ndarray, rcv: np.ndarray, num_nodes: int,
                sweeps: int, rng) -> np.ndarray:
    """Semi-asynchronous label propagation over a symmetric edge list.

    Each sweep computes every node's majority neighbor label (ties break
    to the smaller label) but applies it to a random HALF of the nodes —
    synchronous LPA on community graphs oscillates on near-bipartite
    motifs and strands ~40% of nodes as singletons (measured); the half
    update converges instead.  Vectorized: two lexsorts + run-length
    counts per sweep, O(E log E).
    """
    lab = np.arange(num_nodes, dtype=np.int64)
    for _ in range(sweeps):
        nl = lab[snd]
        o = np.lexsort((nl, rcv))
        r_s, l_s = rcv[o], nl[o]
        new_pair = np.r_[True, (r_s[1:] != r_s[:-1]) | (l_s[1:] != l_s[:-1])]
        starts = np.flatnonzero(new_pair)
        counts = np.diff(np.r_[starts, len(r_s)])
        pr, pl = r_s[starts], l_s[starts]
        ordp = np.lexsort((-counts, pr))
        firsts = np.flatnonzero(np.r_[True, pr[ordp][1:] != pr[ordp][:-1]])
        upd_r, upd_l = pr[ordp][firsts], pl[ordp][firsts]
        m = rng.random(len(upd_r)) < 0.5
        lab2 = lab.copy()
        lab2[upd_r[m]] = upd_l[m]
        lab = lab2
    return lab


def community_order(edges: np.ndarray, num_nodes: int,
                    sweeps: int = 16, split_rounds: int = 2,
                    split_above: int = 1024, seed: int = 0) -> np.ndarray:
    """Community-clustered relabeling: LPA groups + BFS-rank interleave.

    :func:`locality_order`'s plain BFS mixes communities at every
    frontier expansion — on a community-structured power-law graph at
    arxiv scale it recovers only ~21% block-clusterable edges where the
    planted-partition oracle reaches ~41%.  This ordering first detects
    communities with semi-async label propagation (giant labels get
    re-clustered on their internal subgraph), then orders nodes by
    (community's first BFS rank, BFS rank): communities become
    contiguous id ranges, adjacent communities stay near each other, and
    within a community the BFS rank preserves neighborhood locality —
    measured ~31% clusterable on the same graph (docs/benchmarks.md
    r04).  Pure host-side numpy, ~20 s at arxiv scale (one-time prep,
    amortized over the whole training run).  Like the BFS order this is
    a graph isomorphism: only the memory layout changes.
    """
    e = np.asarray(edges, np.int64)
    _check_edge_range(e, num_nodes)
    rng = np.random.default_rng(seed)
    sym = np.concatenate([e, e[:, ::-1]], axis=0)
    snd, rcv = sym[:, 0], sym[:, 1]
    lab = _lpa_sweeps(snd, rcv, num_nodes, sweeps, rng)
    for _ in range(split_rounds):
        szmap = np.bincount(lab)
        big = szmap[lab] > split_above
        keep = big[snd] & big[rcv] & (lab[snd] == lab[rcv])
        if not keep.sum():
            break
        sub = _lpa_sweeps(snd[keep], rcv[keep], num_nodes, max(sweeps - 6, 4),
                          rng)
        lab = np.where(big, lab.max() + 1 + sub, lab)
    bfs = locality_order(e, num_nodes)
    rank = np.empty(num_nodes, np.int64)
    rank[bfs] = np.arange(num_nodes)
    minr = np.full(int(lab.max()) + 1, num_nodes, np.int64)
    np.minimum.at(minr, lab, rank)
    return np.lexsort((rank, minr[lab]))


def apply_locality_order(edges: np.ndarray, x: np.ndarray,
                         labels: Optional[np.ndarray] = None,
                         method: str = "bfs", cache: Any = "auto"):
    """Relabel a loaded graph with :func:`locality_order` (``method=
    "bfs"``) or :func:`community_order` (``method="community"`` — better
    block density on community-structured graphs, costlier host prep).

    Returns (edges, x, labels, order) with node ``order[rank]`` renamed
    to ``rank``; pass the result straight to :func:`prepare` /
    :func:`split_edges`.  The order array is deterministic in (edges, n,
    method), so it caches persistently (``cache`` — see :func:`prepare`;
    the community order is ~20 s of host work at arxiv scale).
    """
    n = x.shape[0]
    if method not in ("community", "bfs"):
        raise ValueError(f"unknown reorder method {method!r}")
    from hyperspace_tpu.data import prep_cache

    e_arr = np.asarray(edges, np.int64)
    pc = prep_cache.resolve(
        cache, auto_ok=len(e_arr) >= _CACHE_AUTO_MIN_EDGES)
    build = lambda: (community_order(e_arr, n) if method == "community"
                     else locality_order(e_arr, n))
    if pc is not None:
        order = pc.get_or_build("local-order", (e_arr, n, method), build)
    else:
        order = build()
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n)
    new_edges = rank[np.asarray(edges, np.int64)]
    new_x = np.asarray(x)[order]
    new_labels = None if labels is None else np.asarray(labels)[order]
    return new_edges, new_x, new_labels, order
