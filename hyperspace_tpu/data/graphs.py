"""Graph datasets for HGCN: Cora / ogbn-arxiv loaders + synthetic fallbacks.

Reference workload 2 (BASELINE.json configs[1]): hyperbolic GCN on
Cora / ogbn-arxiv in the Lorentz model — the north-star benchmark
(SURVEY.md §0, §3.2).

TPU constraint (SURVEY.md §7 hard-part #3): XLA wants static shapes, so the
edge list is **padded to a bucket size** and carried with a boolean mask;
aggregation is masked ``segment_sum`` over receivers, never ragged ops.

This environment has no network access, so the loaders read standard
on-disk formats when present (Planetoid ``cora.content``/``cora.cites``;
OGB's extracted csv layout) and otherwise synthesize structurally similar
graphs: a noisy hierarchy (trees embed well in hyperbolic space, so link
prediction ROC-AUC is a meaningful quality signal — the same reason the
reference's workloads are hierarchy-shaped) with community-correlated
features for node classification.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class Graph:
    """A static-shape graph: padded edge list + masks.

    ``senders``/``receivers`` hold one direction per stored edge; callers
    that need symmetric message passing should build the graph through
    :func:`prepare` which symmetrizes and adds self-loops before padding.
    """

    x: np.ndarray  # [N, F] float32 node features
    senders: np.ndarray  # [E_pad] int32
    receivers: np.ndarray  # [E_pad] int32, sorted ascending (see prepare)
    edge_mask: np.ndarray  # [E_pad] bool (False = padding)
    num_nodes: int
    rev_perm: np.ndarray | None = None  # [E_pad] int32 edge -> reverse edge
    deg: np.ndarray | None = None  # [N] float32 masked in-degree (static)
    csr_plan: tuple | None = None  # kernels.segment.CsrPlan work items
    cluster_split: Any | None = None  # kernels.cluster.ClusterSplit (mean agg)
    labels: np.ndarray | None = None  # [N] int32
    num_classes: int = 0
    train_mask: np.ndarray | None = None  # [N] bool (node tasks)
    val_mask: np.ndarray | None = None
    test_mask: np.ndarray | None = None

    @property
    def num_edges(self) -> int:
        return int(self.edge_mask.sum())


class DeviceGraph(NamedTuple):
    """Device-resident graph arrays, one pytree leaf per field.

    The single argument models/layers take for message passing; built once
    per graph with :func:`to_device`.  Optional fields are ``None`` when
    the graph was not built by :func:`prepare` (consumers then fall back
    to plain masked segment ops).
    """

    x: "jax.Array"                      # [N, F]
    senders: "jax.Array"                # [E] int32
    receivers: "jax.Array"              # [E] int32 sorted
    edge_mask: "jax.Array"              # [E] bool
    num_nodes: int                      # static (python int)
    rev_perm: Optional["jax.Array"] = None   # [E] int32 involution
    deg: Optional["jax.Array"] = None        # [N] f32 masked in-degree
    plan: Optional[tuple] = None             # 3 × [T] int32 CSR work items
    cluster: Any = None                      # nn.scatter.ClusterAgg (mean agg)


# num_nodes must stay a static (hashable) field across jit boundaries, so
# DeviceGraph is registered with num_nodes as auxiliary pytree data.
def _dg_flatten(g: DeviceGraph):
    return (g.x, g.senders, g.receivers, g.edge_mask, g.rev_perm, g.deg,
            g.plan, g.cluster), g.num_nodes


def _dg_unflatten(num_nodes, leaves):
    x, s, r, m, rp, deg, plan, cluster = leaves
    return DeviceGraph(x, s, r, m, num_nodes, rp, deg, plan, cluster)


jax.tree_util.register_pytree_node(DeviceGraph, _dg_flatten, _dg_unflatten)


def to_device(g: Graph) -> DeviceGraph:
    """Put a host :class:`Graph` on device as a :class:`DeviceGraph`."""
    cluster = None
    if g.cluster_split is not None:
        from hyperspace_tpu.nn.scatter import ClusterAgg

        cluster = ClusterAgg.from_host(g.cluster_split)
    return DeviceGraph(
        x=jnp.asarray(g.x),
        senders=jnp.asarray(g.senders),
        receivers=jnp.asarray(g.receivers),
        edge_mask=jnp.asarray(g.edge_mask),
        num_nodes=g.num_nodes,
        rev_perm=None if g.rev_perm is None else jnp.asarray(g.rev_perm),
        deg=None if g.deg is None else jnp.asarray(g.deg),
        plan=None if g.csr_plan is None
        else tuple(jnp.asarray(a) for a in g.csr_plan),
        cluster=cluster,
    )


@dataclasses.dataclass
class LinkSplit:
    """Edge split for link prediction (SURVEY.md §3.2 LP head).

    ``graph`` contains only the *training* edges (message passing must not
    see held-out edges).  val/test arrays are [K, 2] (u, v) pairs.
    """

    graph: Graph
    train_pos: np.ndarray
    val_pos: np.ndarray
    val_neg: np.ndarray
    test_pos: np.ndarray
    test_neg: np.ndarray


def _pad_to(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _prepare_edges_numpy(edges, num_nodes, *, symmetrize=True,
                         self_loops=True, pad_multiple=1024):
    """Numpy edge-layout pipeline: the fallback for :func:`prepare` and
    the parity oracle for ``native.prepare_edges`` (tests/data).

    Returns (senders, receivers, mask, rev_perm, deg); ``rev_perm`` is
    None unless ``symmetrize``.
    """
    e = np.asarray(edges, np.int64)
    if symmetrize and len(e):
        e = np.concatenate([e, e[:, ::-1]], axis=0)
    if self_loops:
        loops = np.stack([np.arange(num_nodes)] * 2, axis=1)
        e = np.concatenate([e, loops], axis=0) if len(e) else loops
    # dedupe + sort by (receiver, sender) via flat receiver-major keys
    key = e[:, 1] * num_nodes + e[:, 0]
    e = e[np.unique(key, return_index=True)[1]]
    e_pad = _pad_to(max(len(e), 1), pad_multiple)
    senders = np.full(e_pad, num_nodes - 1, np.int32)
    receivers = np.full(e_pad, num_nodes - 1, np.int32)
    mask = np.zeros(e_pad, bool)
    senders[: len(e)] = e[:, 0]
    receivers[: len(e)] = e[:, 1]
    mask[: len(e)] = True

    rev_perm = None
    if symmetrize:
        # reverse of (s, r) has key s·N + r; keys are sorted, so
        # searchsorted gives its index.  Padding maps to itself.
        keys_sorted = e[:, 1] * num_nodes + e[:, 0]
        rev_perm = np.arange(e_pad, dtype=np.int32)
        rev_perm[: len(e)] = np.searchsorted(
            keys_sorted, e[:, 0] * num_nodes + e[:, 1]).astype(np.int32)
    deg = np.bincount(receivers[mask], minlength=num_nodes).astype(np.float32)
    return senders, receivers, mask, rev_perm, deg


def prepare(
    edges: np.ndarray,
    num_nodes: int,
    x: np.ndarray,
    *,
    symmetrize: bool = True,
    self_loops: bool = True,
    pad_multiple: int = 1024,
    cluster: str | bool = "auto",
    **node_fields,
) -> Graph:
    """Symmetrize, add self-loops, dedupe, sort by receiver, pad.

    TPU layout decisions (SURVEY.md §2 "padding/bucketing needed on TPU"
    and §7 hard-part #3):

    - Edges are **sorted by (receiver, sender)** so every aggregation
      scatter runs XLA's sorted fast path (~2.3× at arxiv scale).
    - ``rev_perm`` maps each edge to its reverse (self-loops and padding
      map to themselves), letting the aggregation VJP scatter sorted too
      (see nn/scatter.py).  Requires ``symmetrize=True``; otherwise left
      ``None`` and consumers fall back to plain segment ops.
    - Padding edges are (N−1, N−1) with ``edge_mask`` False — the max key
      keeps the receiver order sorted; weight 0 keeps them inert.
    - ``deg`` (masked in-degree) and ``csr_plan`` (the block-CSR work-item
      schedule for :func:`hyperspace_tpu.kernels.segment.csr_segment_sum`)
      are static per graph, so they are computed here once instead of per
      training step.
    """
    senders = receivers = mask = rev_perm = deg = None
    try:  # native C++ pipeline; _prepare_edges_numpy is the oracle
        from hyperspace_tpu.data import native

        senders, receivers, mask, rev_perm, deg = native.prepare_edges(
            np.asarray(edges, np.int32), num_nodes, symmetrize=symmetrize,
            self_loops=self_loops, pad_multiple=pad_multiple)
        if not symmetrize:
            rev_perm = None
    except (ImportError, OSError):
        pass
    if senders is None:
        senders, receivers, mask, rev_perm, deg = _prepare_edges_numpy(
            edges, num_nodes, symmetrize=symmetrize, self_loops=self_loops,
            pad_multiple=pad_multiple)

    from hyperspace_tpu.kernels.segment import build_csr_plan

    # cluster-pair split (kernels/cluster.py): avoids the [E, F] message
    # round-trip for block-dense edges.  "auto" builds it only at scales
    # where the aggregation is actually HBM-bound (the one-time host sort
    # is wasted on toy graphs, and small graphs fit the plain path fine).
    split = None
    n_real = int(mask.sum())
    if cluster is True or (cluster == "auto" and n_real >= 200_000):
        if symmetrize:  # the involution backward needs a symmetric set
            from hyperspace_tpu.kernels.cluster import build_cluster_split

            split = build_cluster_split(senders, receivers, mask, deg,
                                        num_nodes)

    return Graph(
        x=np.asarray(x, np.float32),
        senders=senders,
        receivers=receivers,
        edge_mask=mask,
        num_nodes=num_nodes,
        rev_perm=rev_perm,
        deg=deg,
        csr_plan=tuple(build_csr_plan(receivers, num_nodes)),
        cluster_split=split,
        **node_fields,
    )


# --- link-prediction split ----------------------------------------------------


def split_edges(
    edges: np.ndarray,
    num_nodes: int,
    x: np.ndarray,
    *,
    val_frac: float = 0.05,
    test_frac: float = 0.10,
    seed: int = 0,
    pad_multiple: int = 1024,
    **node_fields,
) -> LinkSplit:
    """Hold out edges for LP eval; message passing uses only train edges.

    Negatives are uniform non-edges, the Chami et al. 2019 protocol whose
    ROC-AUC is the [B] quality target.
    """
    rng = np.random.default_rng(seed)
    e = np.asarray(edges, np.int64)
    # undirected canonical form for splitting
    canon = np.sort(e, axis=1)
    canon = canon[np.unique(canon[:, 0] * num_nodes + canon[:, 1], return_index=True)[1]]
    perm = rng.permutation(len(canon))
    n_val = int(len(canon) * val_frac)
    n_test = int(len(canon) * test_frac)
    val_pos = canon[perm[:n_val]]
    test_pos = canon[perm[n_val : n_val + n_test]]
    train_pos = canon[perm[n_val + n_test :]]

    def sample_neg(k: int) -> np.ndarray:
        try:  # native rejection sampler (arxiv-scale edge sets)
            from hyperspace_tpu.data import native

            neg = native.sample_negative_edges(
                canon, num_nodes, k, seed=int(rng.integers(2**31)))
            if len(neg) == k:
                return neg.astype(np.int64)
        except (ImportError, OSError):
            pass
        edge_set = {(int(u), int(v)) for u, v in canon}
        out = []
        while len(out) < k:
            cand = rng.integers(0, num_nodes, size=(2 * (k - len(out)) + 16, 2))
            for u, v in cand:
                if u == v:
                    continue
                a, b = (int(u), int(v)) if u < v else (int(v), int(u))
                if (a, b) in edge_set:
                    continue
                out.append((a, b))
                if len(out) == k:
                    break
        return np.asarray(out, np.int64)

    g = prepare(
        train_pos, num_nodes, x, pad_multiple=pad_multiple, **node_fields
    )
    return LinkSplit(
        graph=g,
        train_pos=train_pos.astype(np.int32),
        val_pos=val_pos.astype(np.int32),
        val_neg=sample_neg(len(val_pos)).astype(np.int32),
        test_pos=test_pos.astype(np.int32),
        test_neg=sample_neg(len(test_pos)).astype(np.int32),
    )


# --- on-disk loaders ----------------------------------------------------------


def load_cora(root: str):
    """Planetoid raw format: ``cora.content`` + ``cora.cites``.

    Returns (edges [E,2], x [N,F], labels [N], num_classes).
    """
    content = os.path.join(root, "cora.content")
    cites = os.path.join(root, "cora.cites")
    ids, feats, labels, label_ids = {}, [], [], {}
    with open(content) as f:
        for line in f:
            parts = line.strip().split()
            ids[parts[0]] = len(ids)
            feats.append([float(t) for t in parts[1:-1]])
            lab = parts[-1]
            label_ids.setdefault(lab, len(label_ids))
            labels.append(label_ids[lab])
    edges = []
    with open(cites) as f:
        for line in f:
            a, b = line.strip().split()
            if a in ids and b in ids:
                edges.append((ids[a], ids[b]))
    return (
        np.asarray(edges, np.int64),
        np.asarray(feats, np.float32),
        np.asarray(labels, np.int32),
        len(label_ids),
    )


def load_ogbn_arxiv(root: str):
    """OGB extracted-csv layout (``raw/edge.csv``, ``raw/node-feat.csv``...)."""
    raw = os.path.join(root, "raw")
    edges = np.loadtxt(os.path.join(raw, "edge.csv"), delimiter=",", dtype=np.int64)
    x = np.loadtxt(os.path.join(raw, "node-feat.csv"), delimiter=",", dtype=np.float32)
    labels = np.loadtxt(os.path.join(raw, "node-label.csv"), delimiter=",", dtype=np.int64)
    return edges, x, labels.astype(np.int32).reshape(-1), int(labels.max()) + 1


# --- synthetic fallbacks ------------------------------------------------------


def synthetic_hierarchy(
    num_nodes: int = 1024,
    branching: int = 3,
    feat_dim: int = 32,
    ancestor_hops: int = 3,
    extra_edge_frac: float = 0.02,
    num_classes: int = 4,
    seed: int = 0,
):
    """A noisy hierarchy with community-correlated features.

    Structure: a ``branching``-ary tree over all nodes, **plus ancestor
    edges up to ``ancestor_hops`` levels** (a truncated transitive closure)
    and a few random cross edges.  The ancestor edges make the graph
    structurally redundant: every held-out link has parallel 2-hop paths
    (child—grandparent—parent), so link prediction from message passing is
    well-posed — a pure tree would disconnect under edge removal and cap
    ROC-AUC near chance.  Hierarchies have strong negative curvature, so
    hyperbolic models fit them well — the signal the integration tests
    assert (SURVEY.md §4.7).

    Class = top-level subtree; features = class prototype + noise + a depth
    coordinate.  Returns (edges [E,2], x [N,F], labels [N], num_classes).
    """
    rng = np.random.default_rng(seed)
    parent = np.zeros(num_nodes, np.int64)
    parent[1:] = (np.arange(1, num_nodes) - 1) // branching
    edges = []
    for i in range(1, num_nodes):
        anc = i
        for _ in range(max(1, ancestor_hops)):
            anc = int(parent[anc])
            edges.append((i, anc))
            if anc == 0:
                break
    n_extra = int(num_nodes * extra_edge_frac)
    for _ in range(n_extra):
        u, v = rng.integers(0, num_nodes, 2)
        if u != v:
            edges.append((int(u), int(v)))
    edges = np.asarray(edges, np.int64)

    # class of a node = which depth-1 subtree it falls under
    depth = np.zeros(num_nodes, np.int64)
    top = np.zeros(num_nodes, np.int64)
    for i in range(1, num_nodes):
        depth[i] = depth[parent[i]] + 1
        top[i] = i if depth[i] == 1 else top[parent[i]]
    labels = (top % num_classes).astype(np.int32)
    labels[0] = 0

    protos = rng.normal(size=(num_classes, feat_dim)).astype(np.float32)
    x = protos[labels] + 0.4 * rng.normal(size=(num_nodes, feat_dim)).astype(np.float32)
    x[:, 0] = depth / max(depth.max(), 1)
    return edges, x, labels, num_classes


def node_split_masks(num_nodes: int, train_frac=0.6, val_frac=0.2, seed: int = 0):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_nodes)
    n_tr = int(num_nodes * train_frac)
    n_va = int(num_nodes * val_frac)
    tr = np.zeros(num_nodes, bool)
    va = np.zeros(num_nodes, bool)
    te = np.zeros(num_nodes, bool)
    tr[perm[:n_tr]] = True
    va[perm[n_tr : n_tr + n_va]] = True
    te[perm[n_tr + n_va :]] = True
    return tr, va, te


def load_graph(name: str, root: str | None = None, **synth_kw):
    """Dispatch: real dataset if its files exist under ``root``, else synthetic.

    Returns (edges, x, labels, num_classes, source) where source is
    "disk" or "synthetic".
    """
    if root is not None:
        if name == "cora" and os.path.exists(os.path.join(root, "cora.content")):
            return (*load_cora(root), "disk")
        if name == "ogbn-arxiv" and os.path.exists(
            os.path.join(root, "raw", "edge.csv")
        ):
            return (*load_ogbn_arxiv(root), "disk")
    defaults = {"cora": dict(num_nodes=2048, feat_dim=64, num_classes=7),
                "ogbn-arxiv": dict(num_nodes=16384, feat_dim=128, num_classes=40)}
    kw = {**defaults.get(name, {}), **synth_kw}
    return (*synthetic_hierarchy(**kw), "synthetic")


# --- locality reordering ------------------------------------------------------


def locality_order(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """BFS relabeling that clusters neighborhoods into contiguous id
    ranges.

    Returns ``order`` with ``order[rank] = old_id``: BFS from the
    highest-degree node of each component (high-degree seeds keep hub
    neighborhoods contiguous).  Real citation graphs arrive with
    essentially random ids; after this relabeling their community
    structure becomes (receiver-block × sender-block) locality, which is
    what the cluster-pair SpMM kernel (kernels/cluster.py) converts into
    VMEM-tile reuse.  The relabeling is a graph isomorphism — quality
    metrics are unaffected, only the memory layout changes.

    Dispatches to the native C++ BFS (``data/_native/localorder.cc``,
    47× at arxiv scale: 1.14 s → 24 ms) when the toolchain is
    available; the pure-Python deque walk below is the fallback and the
    parity oracle.
    """
    e = np.asarray(edges)
    # validate HERE so native and fallback paths fail identically (the
    # C++ walk would OOB-write silently; the python walk would wrap
    # negative ids)
    if len(e) and (e.min() < 0 or e.max() >= num_nodes):
        raise IndexError(
            f"edge ids out of range [0, {num_nodes}): min {e.min()}, "
            f"max {e.max()}")
    try:
        from hyperspace_tpu.data import native

        return native.locality_order(np.asarray(e, np.int32), num_nodes)
    except (ImportError, OSError):
        return _locality_order_python(e, num_nodes)


def _locality_order_python(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """Pure-Python BFS fallback and parity oracle for locality_order."""
    from collections import deque

    e = np.asarray(edges, np.int64)
    e = np.concatenate([e, e[:, ::-1]], axis=0)
    e = e[np.argsort(e[:, 0], kind="stable")]
    indptr = np.searchsorted(e[:, 0], np.arange(num_nodes + 1))
    nbr = e[:, 1]
    deg = np.diff(indptr)
    seeds = np.argsort(-deg, kind="stable")
    visited = np.zeros(num_nodes, bool)
    out = np.empty(num_nodes, np.int64)
    pos = 0
    si = 0
    q = deque()
    while pos < num_nodes:
        while si < num_nodes and visited[seeds[si]]:
            si += 1
        root = seeds[si]
        visited[root] = True
        q.append(root)
        while q:
            u = q.popleft()
            out[pos] = u
            pos += 1
            for v in nbr[indptr[u] : indptr[u + 1]]:
                if not visited[v]:
                    visited[v] = True
                    q.append(v)
    return out


def apply_locality_order(edges: np.ndarray, x: np.ndarray,
                         labels: Optional[np.ndarray] = None):
    """Relabel a loaded graph with :func:`locality_order`.

    Returns (edges, x, labels, order) with node ``order[rank]`` renamed
    to ``rank``; pass the result straight to :func:`prepare` /
    :func:`split_edges`.
    """
    n = x.shape[0]
    order = locality_order(edges, n)
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n)
    new_edges = rank[np.asarray(edges, np.int64)]
    new_x = np.asarray(x)[order]
    new_labels = None if labels is None else np.asarray(labels)[order]
    return new_edges, new_x, new_labels, order
