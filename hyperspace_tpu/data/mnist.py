"""MNIST loader (reference workload 4: hyperbolic VAE on MNIST).

Reads the standard IDX files (``train-images-idx3-ubyte`` etc., raw or
.gz) when a directory with them exists; this environment has no network
access, so the fallback synthesizes an MNIST-shaped dataset of class-
conditioned binary blob images — sufficient for the HVAE integration test
(ELBO must improve; SURVEY.md §4.7) and for benchmarking shapes.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct

import numpy as np


@dataclasses.dataclass
class ImageDataset:
    images: np.ndarray  # [N, H, W] float32 in [0, 1]
    labels: np.ndarray  # [N] int32

    def split(self, train_frac: float = 0.9, seed: int = 0):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self.labels))
        n_tr = int(len(perm) * train_frac)
        pick = lambda idx: ImageDataset(self.images[idx], self.labels[idx])
        return pick(perm[:n_tr]), pick(perm[n_tr:])


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">H", f.read(4)[2:])
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def load_idx_dir(root: str, prefix: str = "train") -> ImageDataset:
    def find(stem):
        for suffix in ("", ".gz"):
            p = os.path.join(root, stem + suffix)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(stem)

    images = _read_idx(find(f"{prefix}-images-idx3-ubyte")).astype(np.float32) / 255.0
    labels = _read_idx(find(f"{prefix}-labels-idx1-ubyte")).astype(np.int32)
    return ImageDataset(images, labels)


def synthetic_mnist(
    num_samples: int = 4096,
    num_classes: int = 10,
    size: int = 28,
    seed: int = 0,
) -> ImageDataset:
    """Class-conditioned blob images: each class has a fixed set of blob
    centers; samples add jitter and pixel noise, then binarize-ish."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(4, size - 4, size=(num_classes, 3, 2))
    labels = rng.integers(0, num_classes, num_samples).astype(np.int32)
    yy, xx = np.mgrid[0:size, 0:size]
    images = np.zeros((num_samples, size, size), np.float32)
    jitter = rng.normal(0, 1.0, size=(num_samples, 3, 2))
    for i, y in enumerate(labels):
        img = np.zeros((size, size), np.float32)
        for b in range(3):
            cy, cx = centers[y, b] + jitter[i, b]
            img += np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * 2.0**2))
        images[i] = np.clip(img, 0, 1)
    return ImageDataset(images, labels)


def load_mnist(root: str | None = None, **synth_kw) -> tuple[ImageDataset, str]:
    if root is not None and os.path.isdir(root):
        try:
            return load_idx_dir(root), "disk"
        except FileNotFoundError:
            pass
    return synthetic_mnist(**synth_kw), "synthetic"
