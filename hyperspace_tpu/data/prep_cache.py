"""Persistent on-disk cache for expensive graph preprocessing.

Host graph prep is the slow, deterministic prefix of every big-graph run:
edge layout (symmetrize/dedupe/sort/pad + reverse involution + block-CSR
plan), the cluster-pair split (one host sort over ~2.4 M edges), the
community/BFS locality order (~20 s at arxiv scale), and the LP edge
split.  All of it is a pure function of (input arrays, knobs, code), so
repeat runs — and the bench's realistic disk-graph legs, which rebuild
the identical artifacts every round — can skip the rebuild entirely.

Keying: sha256 over the input arrays' raw bytes (dtype/shape included),
every knob, and a **code fingerprint** (the bytes of the modules that
compute the artifacts — ``data/graphs.py``, ``kernels/cluster.py``,
``kernels/segment.py``, and this file), so editing any producer
invalidates every entry instead of silently serving stale layouts.

Storage: one pickle per entry under ``<repo>/.cache/graphprep`` (already
gitignored), written atomically (tmp + rename) so an interrupted run
never leaves a half-written entry that a later run would load.  A
corrupt/unreadable entry is treated as a miss and rebuilt in place.

Knobs:

- ``HYPERSPACE_CACHE_DIR``      — cache root override.
- ``HYPERSPACE_GRAPH_CACHE=0``  — disables the "auto" default (explicit
  ``cache=True``/``PrepCache`` arguments still work).

Call sites (``data/graphs.py``) default to ``cache="auto"``: caching
engages only at scales where the prep is measurably expensive (the same
~200 k-edge gate as the cluster split), so unit-test-sized graphs never
touch the disk.  Each hit/miss bumps the telemetry registry
(``prep_cache/hit`` / ``prep_cache/miss`` — docs/observability.md), so
the "second run skips rebuild" contract is visible in every JSONL log
record and bench artifact instead of as a scattered stdout line; each
lookup/build/store transaction runs under one ``prep`` trace span, so
cache effectiveness (and a slow cache) shows up as host-timeline time
in ``trace_out=`` dumps.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable, Optional

import numpy as np

# bump to invalidate every entry on format changes
CACHE_FORMAT = 1

# producers whose source participates in the key (paths relative to the
# package root) — edit any of them and every cached artifact misses.
# The native C++ pipeline is the PREFERRED path inside
# _build_edge_layout / sample_negative_edges, so its sources (and the
# ctypes wrapper that dispatches to it) must invalidate too.
_CODE_FILES = (
    os.path.join("data", "graphs.py"),
    os.path.join("data", "prep_cache.py"),
    os.path.join("data", "native.py"),
    os.path.join("data", "_native", "graphprep.cc"),
    os.path.join("data", "_native", "closure.cc"),
    os.path.join("data", "_native", "localorder.cc"),
    os.path.join("data", "_native", "sampler.cc"),
    os.path.join("kernels", "cluster.py"),
    os.path.join("kernels", "segment.py"),
)

_ENV_DIR = "HYPERSPACE_CACHE_DIR"
_ENV_SWITCH = "HYPERSPACE_GRAPH_CACHE"

_code_fp: Optional[str] = None


def default_root() -> str:
    root = os.environ.get(_ENV_DIR)
    if root:
        return os.path.abspath(root)
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".cache", "graphprep")


def auto_enabled() -> bool:
    """Whether ``cache="auto"`` call sites may cache at all."""
    return os.environ.get(_ENV_SWITCH, "1").lower() not in (
        "0", "false", "no", "off")


def code_fingerprint() -> str:
    """sha256 of the producer modules' bytes (memoized per process)."""
    global _code_fp
    if _code_fp is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        h = hashlib.sha256()
        for rel in _CODE_FILES:
            path = os.path.join(pkg, rel)
            h.update(rel.encode())
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"<missing>")
        _code_fp = h.hexdigest()
    return _code_fp


def _update(h, part) -> None:
    """Feed one key part into the hash, type-tagged so e.g. the int 1 and
    the string "1" can never collide."""
    if isinstance(part, np.ndarray):
        a = np.ascontiguousarray(part)
        h.update(f"nd:{a.dtype.str}:{a.shape}:".encode())
        h.update(a.tobytes())
    elif isinstance(part, (tuple, list)):
        h.update(f"seq{len(part)}:".encode())
        for p in part:
            _update(h, p)
    elif isinstance(part, bytes):
        h.update(b"b:" + part)
    else:
        h.update(f"{type(part).__name__}:{part!r};".encode())


def key_hash(kind: str, key_parts) -> str:
    h = hashlib.sha256()
    _update(h, (CACHE_FORMAT, code_fingerprint(), kind, tuple(key_parts)))
    return h.hexdigest()


class PrepCache:
    """Content-addressed pickle store with hit/miss counters."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or default_root())
        self.hits = 0
        self.misses = 0

    def _path(self, kind: str, digest: str) -> str:
        return os.path.join(self.root, f"{kind}-{digest}.pkl")

    def get_or_build(self, kind: str, key_parts, builder: Callable[[], Any]):
        """Load the entry for (kind, key_parts) or build + store it.

        The builder's return value must be picklable (numpy arrays and
        plain containers of them).  Any storage failure degrades to
        building without caching — the cache can slow nothing down and
        break nothing."""
        from hyperspace_tpu.telemetry import registry as telem
        from hyperspace_tpu.telemetry.trace import span

        # ONE span over the whole lookup/build/store: a slow cache (a
        # multi-hundred-MB pickle.load off slow disk) must be visible
        # in the host timeline just like the build it replaces
        with span("prep"):
            digest = key_hash(kind, key_parts)
            path = self._path(kind, digest)
            if os.path.exists(path):
                try:
                    with open(path, "rb") as f:
                        payload = pickle.load(f)
                    self.hits += 1
                    telem.inc("prep_cache/hit")
                    return payload
                except Exception:  # noqa: BLE001 — corrupt entry = miss
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            payload = builder()
            self.misses += 1
            telem.inc("prep_cache/miss")
            try:
                os.makedirs(self.root, exist_ok=True)
                tmp = path + f".tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, path)
            except OSError:
                pass  # read-only checkout etc.: serve the built value
            return payload


_default: Optional[PrepCache] = None


def default_cache() -> PrepCache:
    global _default
    if _default is None:
        _default = PrepCache()
    return _default


def stats() -> dict:
    """Process-wide default-cache counters (bench observability)."""
    if _default is None:
        return {"hits": 0, "misses": 0}
    return {"hits": _default.hits, "misses": _default.misses}


def resolve(cache, *, auto_ok: bool) -> Optional[PrepCache]:
    """Normalize a call-site ``cache`` argument.

    ``None``/``False`` → off; ``True`` → the default cache; a
    :class:`PrepCache` → itself; ``"auto"`` → the default cache iff the
    call site says the workload is big enough (``auto_ok``) AND the env
    switch has not disabled auto caching."""
    if cache is None or cache is False:
        return None
    if isinstance(cache, PrepCache):
        return cache
    if cache is True:
        return default_cache()
    if cache == "auto":
        return default_cache() if (auto_ok and auto_enabled()) else None
    raise ValueError(f"unknown cache argument {cache!r}")
