"""ctypes bindings for the native C++ data helpers (SURVEY.md §2 "Data").

Compiles ``_native/closure.cc`` with g++ on first use into the package's
``_native`` directory (cached by source mtime) and exposes:

- :func:`transitive_closure` — WordNet-scale DAG closure (the hook
  :mod:`hyperspace_tpu.data.wordnet` dispatches to),
- :func:`sample_negative_edges` — rejection-sampled LP negatives at
  arxiv scale (used by :mod:`hyperspace_tpu.data.graphs`).

No pybind11 in this environment: plain C ABI + ctypes (the sanctioned
binding route).  Raises ImportError if no C++ toolchain is available, and
callers fall back to their pure-Python/numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "_native")
_SRCS = [os.path.join(_DIR, "closure.cc"), os.path.join(_DIR, "graphprep.cc"),
         os.path.join(_DIR, "localorder.cc"), os.path.join(_DIR, "sampler.cc")]
_LIB = os.path.join(_DIR, "libhsdata.so")

_lib = None


def _build() -> str:
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise ImportError("no C++ compiler for hyperspace_tpu native helpers")
    src_mtime = max(os.path.getmtime(s) for s in _SRCS)
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < src_mtime:
        cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", *_SRCS,
               "-o", _LIB + ".tmp"]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except subprocess.CalledProcessError as e:  # callers fall back on
            raise ImportError(                      # ImportError (module doc)
                f"native helper build failed: {e.stderr.decode()[:500]}") from e
        os.replace(_LIB + ".tmp", _LIB)
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_build())
    lib.closure_compute.restype = ctypes.c_void_p
    lib.closure_compute.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32]
    lib.pairbuf_size.restype = ctypes.c_int64
    lib.pairbuf_size.argtypes = [ctypes.c_void_p]
    lib.pairbuf_copy.restype = None
    lib.pairbuf_copy.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.pairbuf_free.restype = None
    lib.pairbuf_free.argtypes = [ctypes.c_void_p]
    lib.sample_negative_edges.restype = ctypes.c_int64
    lib.sample_negative_edges.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32)]
    lib.graph_prepare.restype = ctypes.c_void_p
    lib.graph_prepare.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.graph_prepare_copy.restype = None
    lib.graph_prepare_copy.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int32]
    lib.graph_prepare_free.restype = None
    lib.graph_prepare_free.argtypes = [ctypes.c_void_p]
    lib.locality_order.restype = None
    lib.locality_order.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64)]
    lib.sample_neighbors.restype = None
    lib.sample_neighbors.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return lib


def _as_i32_pairs(a: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(np.asarray(a, np.int32))
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError(f"expected [N, 2] pairs, got {a.shape}")
    return a


def transitive_closure(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """All (node, ancestor) pairs of the parent DAG; [P, 2] int32."""
    lib = _load()
    e = _as_i32_pairs(edges)
    ptr = e.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    handle = lib.closure_compute(ptr, e.shape[0], int(num_nodes))
    try:
        n = lib.pairbuf_size(handle)
        out = np.empty((n, 2), np.int32)
        if n:
            lib.pairbuf_copy(handle, out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)))
    finally:
        lib.pairbuf_free(handle)
    return out


def prepare_edges(
    edges: np.ndarray,
    num_nodes: int,
    *,
    symmetrize: bool = True,
    self_loops: bool = True,
    pad_multiple: int = 1024,
):
    """Native edge-layout pipeline (symmetrize → self-loops → dedupe →
    receiver-major sort → pad → reverse involution → in-degree).

    Returns (senders, receivers, mask, rev_perm, deg) matching the numpy
    path in :func:`hyperspace_tpu.data.graphs.prepare` exactly
    (tests/data/test_native.py asserts bit-equality); ``rev_perm`` is
    only meaningful when ``symmetrize`` — callers drop it otherwise.
    At arxiv scale the two are comparable in wall time (~1 s each); the
    native path keeps the full data-prep pipeline in the C++ layer
    alongside closure/negative-sampling and avoids materializing the
    intermediate int64 edge copies the numpy path allocates.
    """
    lib = _load()
    e = _as_i32_pairs(edges) if len(edges) else np.zeros((0, 2), np.int32)
    e_pad = ctypes.c_int64()
    handle = lib.graph_prepare(
        e.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), e.shape[0],
        int(num_nodes), int(symmetrize), int(self_loops), int(pad_multiple),
        ctypes.byref(e_pad))
    try:
        n = e_pad.value
        senders = np.empty(n, np.int32)
        receivers = np.empty(n, np.int32)
        mask = np.empty(n, np.uint8)
        rev_perm = np.empty(n, np.int32)
        deg = np.empty(num_nodes, np.float32)
        lib.graph_prepare_copy(
            handle,
            senders.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            receivers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            rev_perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            deg.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(num_nodes))
    finally:
        lib.graph_prepare_free(handle)
    return senders, receivers, mask.astype(bool), rev_perm, deg


def locality_order(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """BFS locality relabeling; [N] int64 with ``order[rank] = old id``.

    Exact twin of :func:`hyperspace_tpu.data.graphs.locality_order`
    (same adjacency order and seed tie-breaking — parity-tested).
    """
    lib = _load()
    e = _as_i32_pairs(edges) if len(edges) else np.zeros((0, 2), np.int32)
    # the C++ side does no bounds checks (silent OOB write); fail here the
    # way the numpy twin would (IndexError) instead
    if len(e) and (e.min() < 0 or e.max() >= num_nodes):
        raise IndexError(
            f"edge ids out of range [0, {num_nodes}): min {e.min()}, "
            f"max {e.max()}")
    out = np.empty(num_nodes, np.int64)
    lib.locality_order(
        e.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), e.shape[0],
        int(num_nodes), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out


def sample_neighbors(indptr: np.ndarray, indices: np.ndarray,
                     seeds: np.ndarray, fanout: int,
                     seed: int = 0) -> np.ndarray:
    """[len(seeds), fanout] uniform with-replacement neighbor draws.

    CSR adjacency (``indptr`` int64 [N+1], ``indices`` int32); isolated
    nodes yield themselves.  Per-cell stateless splitmix64 RNG —
    :func:`sample_neighbors_numpy` is the bit-exact oracle.
    """
    lib = _load()
    indptr = np.ascontiguousarray(indptr, np.int64)
    indices = np.ascontiguousarray(indices, np.int32)
    seeds = np.ascontiguousarray(seeds, np.int32)
    # the C++ side does no bounds checks (silent OOB read); fail here the
    # way the numpy twin would (IndexError) instead
    if len(seeds) and (seeds.min() < 0 or seeds.max() >= len(indptr) - 1):
        raise IndexError(
            f"seed ids out of range [0, {len(indptr) - 1}): "
            f"min {seeds.min()}, max {seeds.max()}")
    out = np.empty((len(seeds), fanout), np.int32)
    lib.sample_neighbors(
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(seeds), int(fanout), int(seed) & (2**64 - 1),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def sample_neighbors_numpy(indptr: np.ndarray, indices: np.ndarray,
                           seeds: np.ndarray, fanout: int,
                           seed: int = 0) -> np.ndarray:
    """Vectorized numpy twin of :func:`sample_neighbors` — same splitmix64
    stream per output cell, so the two agree bit-exactly (parity oracle
    and the no-toolchain fallback)."""
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int32)
    seeds = np.asarray(seeds, np.int64)
    # same guard as the native path — without it numpy would wrap
    # negative ids instead of raising, and the twins would diverge
    if len(seeds) and (seeds.min() < 0 or seeds.max() >= len(indptr) - 1):
        raise IndexError(
            f"seed ids out of range [0, {len(indptr) - 1}): "
            f"min {seeds.min()}, max {seeds.max()}")
    off = indptr[seeds]                                     # [K]
    deg = indptr[seeds + 1] - off                           # [K]
    cells = (np.arange(len(seeds), dtype=np.uint64)[:, None]
             * np.uint64(fanout)
             + np.arange(fanout, dtype=np.uint64)[None, :])  # [K, f]
    with np.errstate(over="ignore"):
        x = np.uint64(seed) ^ cells
        x += np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    if len(indices) == 0:  # every node isolated: all-self
        return np.broadcast_to(seeds[:, None], (len(seeds), fanout)
                               ).astype(np.int32).copy()
    safe_deg = np.maximum(deg, 1).astype(np.uint64)[:, None]
    # isolated rows (deg 0) produce an in-range dummy pick, then np.where
    # replaces them with the seed itself (the C++ branch does the same)
    pick = np.minimum((x % safe_deg).astype(np.int64) + off[:, None],
                      len(indices) - 1)
    return np.where(deg[:, None] > 0, indices[pick],
                    seeds[:, None]).astype(np.int32)


def sample_negative_edges(
    edges: np.ndarray, num_nodes: int, k: int, seed: int = 0
) -> np.ndarray:
    """k uniform undirected non-edges (canonical u<v form); [k, 2] int32."""
    lib = _load()
    e = _as_i32_pairs(edges)
    out = np.empty((k, 2), np.int32)
    got = lib.sample_negative_edges(
        e.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), e.shape[0],
        int(num_nodes), int(k), int(seed) & (2**64 - 1),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out[:got]
