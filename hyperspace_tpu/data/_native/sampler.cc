// Uniform neighbor sampling for minibatch (GraphSAGE-style) HGCN
// training: the host-side data-loader hot path that fills the static
// [B, f1], [B, f1, f2], ... index blocks the jitted sampled train step
// consumes.  Stateless per-cell RNG (splitmix64 of seed ^ cell index) so
// the numpy oracle in data/native.py reproduces every draw bit-exactly
// (tests/data/test_native.py).
//
// Sampling is uniform WITH replacement over the node's adjacency list;
// a node with no neighbors yields itself (the sampled aggregation then
// weights its neighbor sum by zero — see models/hgcn_sampled.py).

#include <cstdint>

extern "C" {

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// indptr: [num_nodes + 1] int64 CSR row offsets; indices: neighbor ids.
// seeds: [n_seeds] int32 nodes to sample for.  out: [n_seeds * fanout].
void sample_neighbors(const int64_t* indptr, const int32_t* indices,
                      const int32_t* seeds, int64_t n_seeds, int32_t fanout,
                      uint64_t seed, int32_t* out) {
  for (int64_t i = 0; i < n_seeds; ++i) {
    const int32_t u = seeds[i];
    const int64_t off = indptr[u];
    const int64_t deg = indptr[u + 1] - off;
    for (int32_t j = 0; j < fanout; ++j) {
      const int64_t cell = i * fanout + j;
      if (deg == 0) {
        out[cell] = u;  // isolated: self (weighted 0 by the aggregator)
      } else {
        const uint64_t r = splitmix64(seed ^ static_cast<uint64_t>(cell));
        out[cell] = indices[off + static_cast<int64_t>(r % deg)];
      }
    }
  }
}

}  // extern "C"
