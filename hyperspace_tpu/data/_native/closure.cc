// Native data-prep helpers for hyperspace_tpu (SURVEY.md §2 "Data" rows).
//
// The reference's data pipeline is native (C++/CUDA); the TPU rebuild keeps
// host-side graph preprocessing native too: transitive closure of the
// hypernymy DAG (WordNet-scale: 82k nodes / ~750k closure pairs) and
// rejection-sampled negative edges for link prediction (arxiv-scale edge
// sets).  Exposed through ctypes (no pybind11 in this environment); see
// hyperspace_tpu/data/native.py for the Python side.
//
// Build: g++ -O2 -shared -fPIC closure.cc -o libhsdata.so

#include <cstdint>
#include <cstring>
#include <random>
#include <unordered_set>
#include <vector>

extern "C" {

struct PairBuf {
  std::vector<int32_t> flat;  // (u, v) pairs, flattened
};

// ---- transitive closure ----------------------------------------------------

// edges: [n_edges * 2] (child, parent).  Returns a PairBuf* handle holding
// all (node, ancestor) pairs reachable through the parent relation.
void* closure_compute(const int32_t* edges, int64_t n_edges,
                      int32_t num_nodes) {
  std::vector<std::vector<int32_t>> parents(num_nodes);
  for (int64_t i = 0; i < n_edges; ++i) {
    int32_t u = edges[2 * i], v = edges[2 * i + 1];
    if (u >= 0 && u < num_nodes && v >= 0 && v < num_nodes)
      parents[u].push_back(v);
  }
  auto* out = new PairBuf();
  // iterative DFS per node; `seen` is epoch-stamped to avoid re-clearing
  std::vector<int32_t> stamp(num_nodes, -1);
  std::vector<int32_t> stack;
  for (int32_t start = 0; start < num_nodes; ++start) {
    stack.assign(parents[start].begin(), parents[start].end());
    while (!stack.empty()) {
      int32_t p = stack.back();
      stack.pop_back();
      if (stamp[p] == start) continue;
      stamp[p] = start;
      out->flat.push_back(start);
      out->flat.push_back(p);
      for (int32_t q : parents[p])
        if (stamp[q] != start) stack.push_back(q);
    }
  }
  return out;
}

int64_t pairbuf_size(void* handle) {  // number of pairs
  return static_cast<PairBuf*>(handle)->flat.size() / 2;
}

void pairbuf_copy(void* handle, int32_t* dst) {
  auto* buf = static_cast<PairBuf*>(handle);
  std::memcpy(dst, buf->flat.data(), buf->flat.size() * sizeof(int32_t));
}

void pairbuf_free(void* handle) { delete static_cast<PairBuf*>(handle); }

// ---- negative-edge sampling ------------------------------------------------

// Uniform (u, v) non-edges, u != v, rejecting members of the undirected
// edge set.  edges: [n_edges * 2] canonical (min, max) pairs.  Fills
// out[2*k]; returns k actually sampled (k unless the graph is near-complete
// and max_tries is exhausted).
int64_t sample_negative_edges(const int32_t* edges, int64_t n_edges,
                              int32_t num_nodes, int64_t k, uint64_t seed,
                              int32_t* out) {
  std::unordered_set<int64_t> edge_set;
  edge_set.reserve(static_cast<size_t>(n_edges) * 2);
  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t a = edges[2 * i], b = edges[2 * i + 1];
    if (a > b) std::swap(a, b);
    edge_set.insert(a * num_nodes + b);
  }
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int32_t> uni(0, num_nodes - 1);
  int64_t got = 0, tries = 0;
  const int64_t max_tries = 1000 * (k + 16);
  while (got < k && tries < max_tries) {
    ++tries;
    int64_t a = uni(rng), b = uni(rng);
    if (a == b) continue;
    if (a > b) std::swap(a, b);
    if (edge_set.count(a * num_nodes + b)) continue;
    out[2 * got] = static_cast<int32_t>(a);
    out[2 * got + 1] = static_cast<int32_t>(b);
    ++got;
  }
  return got;
}

}  // extern "C"
