// Native graph preparation: the hot host-side path of
// hyperspace_tpu.data.graphs.prepare (symmetrize, self-loops, dedupe,
// receiver-major sort, pad, reverse-edge involution, in-degree) for
// arxiv-scale edge lists.  The numpy implementation stays as the
// fallback and the parity oracle (tests/data/test_native.py).
//
// Plain C ABI for ctypes (no pybind11 in this environment); the caller
// owns numpy buffers and we copy into them, mirroring closure.cc.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

struct PreparedGraph {
  std::vector<int32_t> senders, receivers, rev_perm;
  std::vector<uint8_t> mask;
  std::vector<float> deg;
  int64_t e_pad = 0;
};

// Builds the padded, receiver-sorted symmetric edge layout.
// edges: [n_edges, 2] int32 (sender, receiver) pairs.
// Returns an opaque handle; *out_e_pad receives the padded edge count.
void* graph_prepare(const int32_t* edges, int64_t n_edges, int32_t num_nodes,
                    int32_t symmetrize, int32_t self_loops,
                    int64_t pad_multiple, int64_t* out_e_pad) {
  const int64_t n = num_nodes;
  std::vector<int64_t> keys;  // receiver-major flat key: r * n + s
  keys.reserve((symmetrize ? 2 * n_edges : n_edges) +
               (self_loops ? n : 0));
  for (int64_t i = 0; i < n_edges; ++i) {
    const int64_t s = edges[2 * i], r = edges[2 * i + 1];
    keys.push_back(r * n + s);
    if (symmetrize) keys.push_back(s * n + r);
  }
  if (self_loops)
    for (int64_t v = 0; v < n; ++v) keys.push_back(v * n + v);
  std::sort(keys.begin(), keys.end());
  keys.erase(std::unique(keys.begin(), keys.end()), keys.end());

  const int64_t e = static_cast<int64_t>(keys.size());
  const int64_t m = pad_multiple > 0 ? pad_multiple : 1;
  const int64_t e_pad = ((std::max<int64_t>(e, 1) + m - 1) / m) * m;

  auto* out = new PreparedGraph();
  out->e_pad = e_pad;
  out->senders.assign(e_pad, num_nodes - 1);   // padding: (N-1, N-1)
  out->receivers.assign(e_pad, num_nodes - 1);
  out->mask.assign(e_pad, 0);
  out->rev_perm.resize(e_pad);
  out->deg.assign(n, 0.0f);
  for (int64_t i = 0; i < e_pad; ++i)
    out->rev_perm[i] = static_cast<int32_t>(i);  // padding maps to itself
  for (int64_t i = 0; i < e; ++i) {
    const int64_t r = keys[i] / n, s = keys[i] % n;
    out->senders[i] = static_cast<int32_t>(s);
    out->receivers[i] = static_cast<int32_t>(r);
    out->mask[i] = 1;
    out->deg[r] += 1.0f;
    if (symmetrize) {
      // reverse of (s, r) has key s*n + r; keys are sorted & complete
      const int64_t rev = std::lower_bound(keys.begin(), keys.end(),
                                           s * n + r) - keys.begin();
      out->rev_perm[i] = static_cast<int32_t>(rev);
    }
  }
  *out_e_pad = e_pad;
  return out;
}

void graph_prepare_copy(void* handle, int32_t* senders, int32_t* receivers,
                        uint8_t* mask, int32_t* rev_perm, float* deg,
                        int32_t num_nodes) {
  auto* g = static_cast<PreparedGraph*>(handle);
  std::memcpy(senders, g->senders.data(), g->e_pad * sizeof(int32_t));
  std::memcpy(receivers, g->receivers.data(), g->e_pad * sizeof(int32_t));
  std::memcpy(mask, g->mask.data(), g->e_pad * sizeof(uint8_t));
  std::memcpy(rev_perm, g->rev_perm.data(), g->e_pad * sizeof(int32_t));
  std::memcpy(deg, g->deg.data(), num_nodes * sizeof(float));
}

void graph_prepare_free(void* handle) {
  delete static_cast<PreparedGraph*>(handle);
}

}  // extern "C"
