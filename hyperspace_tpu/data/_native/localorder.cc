// BFS locality relabeling: native twin of
// hyperspace_tpu.data.graphs.locality_order (same traversal and
// tie-breaking; tests/data/test_native.py asserts exact equality with
// the numpy/deque implementation).  Real citation graphs arrive with
// random ids; this one-time host pass turns community structure into
// (receiver-block x sender-block) locality for the cluster-pair SpMM
// kernel, and the Python BFS was the slowest remaining host-prep stage
// at arxiv scale (measured: 1.14 s vs 24 ms here).

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// edges: [n_edges, 2] int32 (u, v) pairs, undirected semantics.
// order_out: [num_nodes] int64, order_out[rank] = old id.
void locality_order(const int32_t* edges, int64_t n_edges,
                    int32_t num_nodes, int64_t* order_out) {
  const int64_t n = num_nodes;
  // Stable source-major adjacency of the doubled edge list [e; e_rev]:
  // all forward edges of u (ascending index) precede all reversed ones
  // — exactly the order np.argsort(e[:, 0], kind="stable") yields.
  std::vector<int64_t> indptr(n + 1, 0);
  for (int64_t i = 0; i < n_edges; ++i) {
    ++indptr[edges[2 * i] + 1];
    ++indptr[edges[2 * i + 1] + 1];
  }
  std::partial_sum(indptr.begin(), indptr.end(), indptr.begin());
  std::vector<int32_t> nbr(indptr[n]);
  std::vector<int64_t> fill(indptr.begin(), indptr.end() - 1);
  for (int64_t i = 0; i < n_edges; ++i)
    nbr[fill[edges[2 * i]]++] = edges[2 * i + 1];
  for (int64_t i = 0; i < n_edges; ++i)
    nbr[fill[edges[2 * i + 1]]++] = edges[2 * i];

  // Seeds: degree descending, ties by node id — np.argsort(-deg, stable).
  std::vector<int32_t> seeds(n);
  std::iota(seeds.begin(), seeds.end(), 0);
  std::stable_sort(seeds.begin(), seeds.end(), [&](int32_t a, int32_t b) {
    return indptr[a + 1] - indptr[a] > indptr[b + 1] - indptr[b];
  });

  std::vector<uint8_t> visited(n, 0);
  std::vector<int32_t> queue;
  queue.reserve(n);
  int64_t pos = 0, qhead = 0, si = 0;
  while (pos < n) {
    while (si < n && visited[seeds[si]]) ++si;
    const int32_t root = seeds[si];
    visited[root] = 1;
    queue.push_back(root);
    while (qhead < static_cast<int64_t>(queue.size())) {
      const int32_t u = queue[qhead++];
      order_out[pos++] = u;
      for (int64_t j = indptr[u]; j < indptr[u + 1]; ++j) {
        const int32_t v = nbr[j];
        if (!visited[v]) {
          visited[v] = 1;
          queue.push_back(v);
        }
      }
    }
  }
}

}  // extern "C"
