"""Ring sequence parallelism for hyperbolic attention (SURVEY.md §5
"Long-context / sequence parallelism"; first-class per the rebuild plan).

Each device holds one shard of Q and one shard of K/V along the sequence
axis.  K/V shards rotate around the mesh axis with ``ppermute`` (one hop
per step — on TPU this rides the ICI ring), and every device folds each
incoming block into its flash-attention running state (max, denominator,
numerator) — the same online-softmax recurrence as
:func:`hyperspace_tpu.nn.attention.lorentz_attention_tiled`, with blocks
arriving over the network instead of from HBM.  After ``n`` hops every
device has seen the full sequence; the final row-rescale projects the
accumulated Lorentz-centroid numerator back to the hyperboloid.

Wrap with ``shard_map`` over a mesh axis (see ``ring_attention_sharded``).
Communication volume per device: 2 × (L/n) × D per hop, n hops — the
standard ring-attention cost, fully overlapped by XLA's async collectives
on real hardware.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperspace_tpu.manifolds import Lorentz, smath
from hyperspace_tpu.parallel.mesh import pcast_varying, shard_map
from hyperspace_tpu.nn.attention import minkowski_gram


def _fold_block(q, kj, vj, c, beta, tau, carry, mask_j=None):
    """One online-softmax fold of KV block (kj, vj) into the carry;
    ``mask_j`` ([B, Lk_block] bool, batch-level key padding) drops padded
    keys — expanded here to align with logits of any rank."""
    m_run, l_run, s_run = carry
    gram = minkowski_gram(q, kj)
    logits = (2.0 / c + 2.0 * gram + beta) / tau
    if mask_j is not None:
        mj = mask_j.reshape(
            mask_j.shape[0], *([1] * (logits.ndim - 3)), 1, mask_j.shape[-1])
        logits = jnp.where(mj, logits, -jnp.inf)
    m_new = jnp.maximum(m_run, jnp.max(logits, axis=-1))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m_run), m_run - m_safe, -jnp.inf))
    p = jnp.exp(logits - m_safe[..., None])
    l_new = alpha * l_run + jnp.sum(p, axis=-1)
    s_new = alpha[..., None] * s_run + p @ vj
    return m_new, l_new, s_new


def ring_lorentz_attention(
    q: jax.Array,  # [..., Lq_local, D] this device's Q shard
    k: jax.Array,  # [..., Lk_local, D] this device's KV shard
    v: jax.Array,
    manifold: Lorentz,
    axis_name: str,
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    k_mask: Optional[jax.Array] = None,  # [B, Lk_local] bool key padding
) -> jax.Array:
    """Per-device body of ring attention; call inside shard_map.

    Equivalent to :func:`lorentz_attention` over the gathered sequence
    (with ``mask`` broadcast from the batch-level key-padding mask when
    ``k_mask`` is given), without ever materializing it on one device.
    The mask shard rotates around the ring with its KV shard; the
    unmasked path carries no mask at all (no extra collective payload).
    """
    c = jnp.asarray(manifold.c, q.dtype)
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # constants must be marked varying over the ring axis or the fori_loop
    # carry types mismatch under shard_map's manual-axes checking
    # (pcast_varying: version-portable spelling, no-op on 0.4.x)
    m0 = pcast_varying(jnp.full(q.shape[:-1], -jnp.inf, q.dtype), axis_name)
    l0 = pcast_varying(jnp.zeros(q.shape[:-1], q.dtype), axis_name)
    s0 = jnp.zeros_like(q)

    def fold(carry, kvm):
        return _fold_block(q, kvm[0], kvm[1], c, beta, tau, carry,
                           mask_j=(kvm[2] if k_mask is not None else None))

    def body(i, state):
        kvm, carry = state
        # remat per hop: reverse-mode AD of the (scan-converted) ring
        # loop would otherwise SAVE each hop's [Lq_loc, Lk_loc] score
        # tile — O(L²/n) per device, exactly the memory the ring exists
        # to avoid.  checkpoint recomputes the tile from (q, kj) in the
        # backward (the flash-backward recipe), so residual memory stays
        # O(L·D) and long-context training holds in BOTH directions.
        # prevent_cse=False: under scan the CSE barriers are documented
        # unnecessary and would pad every hop with optimization barriers
        carry = jax.checkpoint(fold, prevent_cse=False)(carry, kvm)
        # rotate KV (+ mask) one hop around the ring (skipped data is
        # re-sent; the last hop's permute is dead code XLA removes when n
        # is static)
        kvm = jax.lax.ppermute(kvm, axis_name, perm)
        return kvm, carry

    kvm0 = (k, v) if k_mask is None else (k, v, k_mask)
    (_, (m_f, l_f, s_f)) = jax.lax.fori_loop(
        0, n, body, (kvm0, (m0, l0, s0)))
    s = s_f / smath.clamp_min(l_f, smath.min_norm(q.dtype))[..., None]
    sp = jnp.sum(s[..., 1:] * s[..., 1:], axis=-1, keepdims=True) - s[..., :1] * s[..., :1]
    nrm = smath.safe_sqrt(smath.clamp_min(-sp, smath.eps_for(q.dtype)))
    return s / (smath.sqrt_c(c) * nrm)


def ring_attention_sharded(
    q: jax.Array,  # [..., L, D] full arrays (sharded by the caller's specs)
    k: jax.Array,
    v: jax.Array,
    manifold: Lorentz,
    mesh: Mesh,
    axis: str = "seq",
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    k_mask: Optional[jax.Array] = None,  # [B, L] bool key-padding mask
) -> jax.Array:
    """shard_map wrapper: shards the sequence axis over ``axis`` and runs
    the ring.  Batch/head axes stay replicated across the seq axis.
    ``k_mask`` is batch-level (same contract as the Ulysses wrapper);
    omitting it compiles the maskless ring — no mask ever rides the
    collectives."""
    seq_spec = P(*((None,) * (q.ndim - 2) + (axis, None)))

    if k_mask is None:
        @partial(shard_map, mesh=mesh,
                 in_specs=(seq_spec, seq_spec, seq_spec), out_specs=seq_spec)
        def run(q, k, v):
            return ring_lorentz_attention(
                q, k, v, manifold, axis, beta=beta, tau=tau)

        return run(q, k, v)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(None, axis)),
        out_specs=seq_spec,
    )
    def run(q, k, v, mk):
        return ring_lorentz_attention(
            q, k, v, manifold, axis, beta=beta, tau=tau, k_mask=mk)

    return run(q, k, v, k_mask)
