"""Node-sharded graph aggregation — the pod actually divides the work.

VERDICT r2 measured that the dp axis of `make_sharded_step_lp` shards only
the supervision pairs: the full-graph encoder (~95% of step time) was
replicated on every device, so a dp=8 mesh left 95% of single-device FLOPs
on every chip.  This module shards the *node dimension* instead — the
TPU-native analogue of the reference trainer's graph partitioning
(SURVEY.md §2 N8, §7 hard-part #3):

- **Host-side partition** (:func:`partition_graph`): nodes are split into
  ``ndev`` contiguous blocks (the receiver-sorted edge layout from
  ``data.graphs.prepare`` makes each block's incoming edges a contiguous
  slice); each shard gets its own receiver-local edge list, per-edge mean
  weights, and block-CSR plan, all padded to common static shapes.
- **Device-side aggregation** (:func:`node_sharded_aggregate`): a
  ``shard_map`` over the data-like mesh axes.  Each device all-gathers
  the [N, F] activations over ICI (the one collective; at bf16 this is
  ~N·F·2 bytes, ≪ the E·F gather it feeds), then runs *its shard's*
  gather + block-CSR segment-sum — E/ndev edges and N/ndev output rows
  per device.
- **Symmetric backward without cross-shard scatters**: for a symmetric
  edge list, dh[i] = Σ_{e: s_e=i} w_e·ḡ[r_e] re-indexes through the edge
  involution onto *receiver*-side edges (the nn/scatter.py identity), and
  every receiver-side edge of shard k lives on shard k.  So the backward
  is the same all-gather (of ḡ) + local planned segment-sum, with the
  reverse-edge weights ``w_bwd[e] = 1/deg[s_e]`` precomputed on host.
  No scatter ever crosses a shard boundary.

Mean aggregation uses the involution backward above (the bench- and
quality-default HGCN path).  Attention aggregation node-shards too —
receiver partitioning keeps its segment softmax shard-local, so
:func:`node_sharded_att_aggregate` runs it with plain autodiff
collectives (all-gather forward, psum-scatter backward) at a somewhat
worse constant than the mean path.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperspace_tpu.data import graphs as graph_data
from hyperspace_tpu.kernels.segment import build_csr_plan, csr_segment_sum
from hyperspace_tpu.parallel.mesh import shard_map

_BN = 128   # node-block rows (must match kernels.segment._BN tiling)
_BK = 512   # edge-chunk size (must match kernels.segment._BK)


class NodeShardedGraph(NamedTuple):
    """Device-resident node-sharded graph (pytree; statics in aux data).

    Per-edge arrays are [ndev, E_s] so a ``P(axes, None)`` sharding gives
    each device exactly its shard's slice; ``senders`` hold *global* node
    ids (they index the all-gathered activations), ``recv`` holds
    *shard-local* receiver ids, ascending within each shard.

    When ``halo`` is set, ``senders`` instead hold *extended-local* ids
    into ``concat(h_local, halo_rows)`` and the exchange runs one of
    two schedules (``halo_kind``):

    - ``"a2a"``: one ``all_to_all`` over [ndev, H, F] send slots, every
      ordered pair padded to the global max H.  ONE collective — the
      schedule the XLA compiled-cost model prices lowest, because cost
      analysis charges every consumer of a buffer its FULL operand
      bytes, so multi-op schedules pay an accounting penalty per op.
    - ``"ppermute"``: one ``ppermute`` per kept ring distance
      d ∈ ``halo_dists``, each padded to its own max H_d
      (``halo_sizes``), slicing one gathered [ΣH_d, F] send buffer.
      Σ_d H_d ≪ ndev·H when hub-heavy pairs skew the per-pair maxima —
      the lowest TRUE interconnect volume — but the per-slice operand
      accounting above makes it measure worse in compiled bytes.

    ``partition_graph(halo="auto")`` picks the layout (or the plain
    all-gather) by ESTIMATED compiled bytes — the metric this
    environment can actually measure; on real multi-chip ICI the
    ppermute schedule's lower row volume may win and can be forced
    with ``halo="ppermute"``.
    """

    x: Any          # [N_pad, F] node features, node-sharded
    senders: Any    # [ndev, E_s] int32 sender ids (global, or ext-local)
    recv: Any       # [ndev, E_s] int32 local receiver ids (sorted)
    w_fwd: Any      # [ndev, E_s] f32 forward mean weights (0 on padding)
    w_bwd: Any      # [ndev, E_s] f32 reverse-edge weights (0 on padding)
    plan: tuple     # 3 × [ndev, T] int32 padded block-CSR work items
    num_nodes: int  # static: real node count (< N_pad)
    n_shard: int    # static: nodes per shard (N_pad = n_shard · ndev)
    mesh: Any       # static: jax.sharding.Mesh
    axes: tuple     # static: data-like mesh axis names the nodes shard over
    send_idx: Any = None     # [ndev, ndev, H] (a2a) | [ndev, ΣH_d] (ppermute)
    halo: bool = False       # static: exchange halo rows, not all-gather
    halo_kind: str = "a2a"   # static: "a2a" | "ppermute"
    halo_dists: tuple = ()   # static: kept ring distances (ppermute)
    halo_sizes: tuple = ()   # static: H_d per kept distance (ppermute)


def _nsg_flatten(g: NodeShardedGraph):
    return ((g.x, g.senders, g.recv, g.w_fwd, g.w_bwd, g.plan, g.send_idx),
            (g.num_nodes, g.n_shard, g.mesh, g.axes, g.halo, g.halo_kind,
             g.halo_dists, g.halo_sizes))


def _nsg_unflatten(aux, leaves):
    x, s, r, wf, wb, plan, send_idx = leaves
    (num_nodes, n_shard, mesh, axes, halo, halo_kind, halo_dists,
     halo_sizes) = aux
    return NodeShardedGraph(x, s, r, wf, wb, plan, num_nodes, n_shard,
                            mesh, axes, send_idx, halo, halo_kind,
                            halo_dists, halo_sizes)


jax.tree_util.register_pytree_node(NodeShardedGraph, _nsg_flatten, _nsg_unflatten)


def data_axes(mesh: Mesh) -> tuple:
    """The data-like axes of ``mesh`` (nodes shard over these)."""
    return tuple(a for a in ("host", "data") if a in mesh.axis_names)


class HostPartition(NamedTuple):
    """Host-side (numpy) result of :func:`partition_graph`."""

    x: np.ndarray        # [N_pad, F]
    senders: np.ndarray  # [ndev, E_s] global (or extended-local if halo)
    recv: np.ndarray     # [ndev, E_s] local sorted
    w_fwd: np.ndarray    # [ndev, E_s]
    w_bwd: np.ndarray    # [ndev, E_s]
    plan: tuple          # 3 × [ndev, T]
    num_nodes: int
    n_shard: int
    send_idx: np.ndarray | None = None  # halo only (layout per halo_kind)
    halo: bool = False
    halo_kind: str = "a2a"
    halo_dists: tuple = ()   # kept ring distances (ppermute)
    halo_sizes: tuple = ()   # H_d per kept distance (ppermute)


def partition_graph(g: graph_data.Graph, ndev: int,
                    bn: int = _BN, bk: int = _BK,
                    halo: Any = "auto") -> HostPartition:
    """Partition a `prepare`-built symmetric graph into ``ndev`` node shards.

    Requires ``g`` built by ``data.graphs.prepare(symmetrize=True)`` (so
    the receiver-sorted layout, the masked degree, and the edge involution
    invariants hold — the backward identity needs every edge's reverse to
    exist).  Shard k owns nodes [k·n_shard, (k+1)·n_shard) and exactly the
    edges whose receiver falls in that range.

    Plan padding: every shard's edge list ends with one full all-padding
    chunk, and plan rows are padded with (last block, last chunk,
    first=0) items — the padding chunk's values are zero, so the extra
    work items are exact no-ops in the kernel.
    """
    if g.rev_perm is None or g.deg is None:
        raise ValueError(
            "partition_graph needs a symmetric prepare()-built graph "
            "(rev_perm/deg missing)")
    n = g.num_nodes
    per_dev = -(-n // ndev)                 # ceil(n / ndev)
    n_shard = (-(-per_dev // bn)) * bn      # rounded up to whole node blocks
    n_pad = n_shard * ndev

    x = np.zeros((n_pad, g.x.shape[1]), np.float32)
    x[:n] = g.x

    mask = np.asarray(g.edge_mask)
    s = np.asarray(g.senders)[mask]
    r = np.asarray(g.receivers)[mask]
    deg = np.maximum(np.asarray(g.deg), 1.0)

    bounds = np.searchsorted(r, np.arange(ndev + 1) * n_shard)
    counts = np.diff(bounds)
    # every shard ends with ≥ one full all-padding chunk so padded plan
    # items always have an inert chunk to point at
    e_s = (-(-max(int(counts.max()), 1) // bk)) * bk + bk

    senders = np.zeros((ndev, e_s), np.int32)
    recv = np.full((ndev, e_s), n_shard - 1, np.int32)
    w_fwd = np.zeros((ndev, e_s), np.float32)
    w_bwd = np.zeros((ndev, e_s), np.float32)
    plans = []
    for k in range(ndev):
        lo, hi = bounds[k], bounds[k + 1]
        m = hi - lo
        senders[k, :m] = s[lo:hi]
        recv[k, :m] = r[lo:hi] - k * n_shard
        w_fwd[k, :m] = 1.0 / deg[r[lo:hi]]
        # weight of the reverse edge (r, s): 1/deg of ITS receiver, s —
        # the backward identity's w∘π without any cross-shard lookup
        w_bwd[k, :m] = 1.0 / deg[s[lo:hi]]
        plans.append(build_csr_plan(recv[k], n_shard, bn, bk))

    t_max = max(p.block.shape[0] for p in plans)
    nb, nchunks = n_shard // bn, e_s // bk
    plan = tuple(np.full((ndev, t_max), fill, np.int32)
                 for fill in (nb - 1, nchunks - 1, 0))
    for k, p in enumerate(plans):
        t = p.block.shape[0]
        plan[0][k, :t] = p.block
        plan[1][k, :t] = p.chunk
        plan[2][k, :t] = p.first

    # halo exchange (VERDICT r3 #6 / r4 #4): per-shard sender-row need
    # sets.  Under a locality ordering most referenced rows are local or
    # in a few neighbor shards, so exchanging exactly the needed rows
    # can beat the full [N, F] all-gather (~N_pad rows/device).  Two
    # schedules exist (NodeShardedGraph doc): the one-collective
    # ``all_to_all`` padded to the global per-pair max H, and the
    # per-ring-distance ``ppermute`` chain padded per distance.  The
    # backward needs the SAME rows of ḡ (the involution identity maps
    # it onto this shard's own edges), so one need-set serves both
    # directions.
    #
    # "auto" picks by ESTIMATED COMPILED BYTES (the metric
    # scripts/cost_scaling_probe.py asserts).  XLA's cost analysis
    # charges every consumer its full operand, so each schedule pays
    # accounting well beyond its wire volume (coefficients calibrated
    # against measured dp=16 compiled costs at 4096/F=16 and
    # 16384/F=128 — r05 docs/benchmarks.md "Halo exchange"):
    #   all-gather:  n_pad rows         (the gathered activation block)
    #   a2a:         ~4·ndev·H rows     (send gather + in + out +
    #                concat-consumer re-read)
    #   ppermute:    (2+n_dists)·ΣH_d   (each of the n_dists slices of
    #                the send buffer is charged the WHOLE buffer — the
    #                accounting that makes the lowest TRUE-volume
    #                schedule measure worst)
    # The gate is deliberately conservative toward the all-gather: a
    # halo schedule must win by construction (strong block structure,
    # e.g. the ring-of-cliques / strongly-communitied DC-SBM shapes),
    # not by a modeling coin-flip.
    # identity/type check, not ==: the int 1 equals True but would take
    # neither string branch below and build a broken partition
    if not (halo is False or halo is True
            or halo in ("auto", "a2a", "ppermute")):
        raise ValueError(
            f"halo={halo!r}: want False, True, 'auto', 'a2a' or "
            "'ppermute' (a typo here would silently measure the "
            "auto-gated schedule instead of the forced one)")
    use_halo = False
    halo_kind = "a2a"
    send_idx = None
    halo_dists: tuple = ()
    halo_sizes: tuple = ()
    if halo is not False and ndev > 1:
        need = [[np.zeros(0, np.int64)] * ndev for _ in range(ndev)]
        for k in range(ndev):
            sk = s[bounds[k]:bounds[k + 1]]
            owner = sk // n_shard
            for j in np.unique(owner):
                if int(j) != k:
                    need[k][int(j)] = np.unique(sk[owner == j])
        # per-distance max receive count: at distance d, shard k
        # receives need[k][(k - d) % ndev] and sends need[(k+d)%ndev][k]
        h_d = {}
        for d in range(1, ndev):
            m = max(len(need[(k + d) % ndev][k]) for k in range(ndev))
            if m:
                h_d[d] = -(-m // 8) * 8
        h_max = max(h_d.values(), default=1)
        sum_h = sum(h_d.values())
        est = {
            False: n_shard * ndev,
            "a2a": 4 * ndev * h_max,
            "ppermute": (2 + len(h_d)) * sum_h,
        }
        if not h_d:
            # no cross-shard edges at all: there is nothing to exchange
            # — a "halo" here would build zero-distance ppermute chains
            # (empty concatenate) or all-zero a2a slots; the aggregation
            # is purely local either way, so stay on the gather-free
            # default even when a halo was forced
            use_halo = False
        elif halo in ("a2a", "ppermute", True):
            use_halo = True
            halo_kind = "a2a" if halo is True else halo
        else:  # "auto"
            best = min(est, key=est.get)
            use_halo = best is not False
            halo_kind = best if use_halo else "a2a"
        if use_halo and halo_kind == "a2a":
            send_idx = np.zeros((ndev, ndev, h_max), np.int32)
            for k in range(ndev):
                for j in range(ndev):
                    rows = need[j][k]          # what j needs FROM k
                    send_idx[k, j, :len(rows)] = rows - k * n_shard
        if use_halo and halo_kind == "ppermute":
            halo_dists = tuple(sorted(h_d))
            halo_sizes = tuple(h_d[d] for d in halo_dists)
            send_idx = np.zeros((ndev, sum(halo_sizes)), np.int32)
            col = 0
            for d, hd in zip(halo_dists, halo_sizes):
                for k in range(ndev):
                    rows = need[(k + d) % ndev][k]   # what (k+d) needs FROM k
                    send_idx[k, col:col + len(rows)] = rows - k * n_shard
                col += hd
        if use_halo:
            # extended-local ids.  a2a: halo rows land as [ndev, H]
            # (sender-major), so owner j's block for shard k starts at
            # n_shard + j·H.  ppermute: rows land concatenated in
            # distance order, owner j's block at
            # n_shard + Σ_{d' < dist(k, j)} H_{d'} (same for every k).
            if halo_kind == "ppermute":
                off_d = {}
                acc = n_shard
                for d, hd in zip(halo_dists, halo_sizes):
                    off_d[d] = acc
                    acc += hd
            for k in range(ndev):
                lo, hi = bounds[k], bounds[k + 1]
                sk = s[lo:hi]
                owner = sk // n_shard
                ext = np.zeros(hi - lo, np.int32)
                local = owner == k
                ext[local] = sk[local] - k * n_shard
                for j in np.unique(owner):
                    j = int(j)
                    if j == k:
                        continue
                    sel = owner == j
                    if halo_kind == "a2a":
                        base = n_shard + j * h_max
                    else:
                        base = off_d[(k - j) % ndev]
                    ext[sel] = base + np.searchsorted(need[k][j], sk[sel])
                senders[k, :hi - lo] = ext
                senders[k, hi - lo:] = 0       # padding edges carry w = 0
    return HostPartition(x, senders, recv, w_fwd, w_bwd, plan, n, n_shard,
                         send_idx, use_halo, halo_kind, halo_dists,
                         halo_sizes)


def graph_shardings(g: NodeShardedGraph) -> NodeShardedGraph:
    """Sharding pytree matching ``g`` (for jit in_shardings) — the aux
    statics are copied from ``g`` so the tree structures are identical."""
    sh = NamedSharding(g.mesh, P(g.axes, None))
    return NodeShardedGraph(sh, sh, sh, sh, sh, (sh, sh, sh),
                            g.num_nodes, g.n_shard, g.mesh, g.axes,
                            None if g.send_idx is None else sh,
                            g.halo, g.halo_kind, g.halo_dists,
                            g.halo_sizes)


def to_device_sharded(hp: HostPartition, mesh: Mesh,
                      axes: Optional[tuple] = None) -> NodeShardedGraph:
    """Place a :class:`HostPartition` on ``mesh`` as a NodeShardedGraph."""
    axes = data_axes(mesh) if axes is None else axes
    ndev = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    if hp.senders.shape[0] != ndev:
        raise ValueError(
            f"partition has {hp.senders.shape[0]} shards but mesh axes "
            f"{axes} have extent {ndev}")
    sh = NamedSharding(mesh, P(axes, None))
    put = lambda a: jax.device_put(jnp.asarray(a), sh)
    return NodeShardedGraph(
        x=put(hp.x), senders=put(hp.senders), recv=put(hp.recv),
        w_fwd=put(hp.w_fwd), w_bwd=put(hp.w_bwd),
        plan=tuple(put(a) for a in hp.plan),
        num_nodes=hp.num_nodes, n_shard=hp.n_shard, mesh=mesh, axes=axes,
        send_idx=None if hp.send_idx is None else put(hp.send_idx),
        halo=hp.halo, halo_kind=hp.halo_kind,
        halo_dists=tuple(hp.halo_dists),
        halo_sizes=tuple(hp.halo_sizes))


def shard_graph(g: graph_data.Graph, mesh: Mesh,
                axes: Optional[tuple] = None,
                halo: Any = "auto") -> NodeShardedGraph:
    """partition_graph + to_device_sharded in one call."""
    axes = data_axes(mesh) if axes is None else axes
    ndev = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    return to_device_sharded(partition_graph(g, ndev, halo=halo), mesh, axes)


# --- the sharded aggregation --------------------------------------------------


def _local_segsum(msgs, recv, pb, pc, pf, n_shard):
    """Per-shard sorted segment-sum: block-CSR kernel on TPU, XLA sorted
    scatter elsewhere — same dispatch contract as nn/scatter.py."""
    return csr_segment_sum(msgs, recv, (pb, pc, pf), n_shard)


def _mesh_extent(mesh, axes) -> int:
    out = 1
    for a in axes:
        out *= mesh.shape[a]
    return out


def _halo_rows(vals_l, si_l, axes, kind, dists, sizes, ndev):
    """The halo collective (NodeShardedGraph doc), either kind.

    ``"a2a"``: one gather of [ndev, H, F] send slots + one
    ``all_to_all``; received rows land sender-major — [ndev·H, F].
    ``"ppermute"``: one gather of the [ΣH_d, F] concatenated send rows,
    then one ``ppermute`` per kept distance over its slice; received
    rows land in distance order.  Both match the extended-local id
    layout ``partition_graph`` wrote for that kind.
    """
    if kind == "a2a":
        sendbuf = vals_l[si_l]                 # [ndev, H, F]
        halo = jax.lax.all_to_all(sendbuf, axes, split_axis=0,
                                  concat_axis=0, tiled=False)
        return halo.reshape(-1, vals_l.shape[-1])
    ax = axes[0] if len(axes) == 1 else axes
    sendbuf = vals_l[si_l]                     # [ΣH_d, F] — one gather
    col = 0
    parts = []
    for d, hd in zip(dists, sizes):
        perm = [(i, (i + d) % ndev) for i in range(ndev)]
        parts.append(jax.lax.ppermute(sendbuf[col:col + hd], ax, perm))
        col += hd
    return jnp.concatenate(parts, axis=0)


def _gather_aggregate(mesh, axes, n_shard, h, w, senders, recv, pb, pc, pf,
                      send_idx=None, kind="a2a", dists=(), sizes=()):
    """Collective + local planned aggregation of this shard's edges.

    Default: all_gather(h) over the node-sharding axes, then gather the
    sender rows locally.  With ``send_idx`` (halo mode): each shard
    sends exactly the rows its peers reference — one ``ppermute`` per
    kept ring distance (:func:`_halo_rows`) — and indexes
    ``concat(h_local, halo)``: 2·Σ_d H_d rows of interconnect traffic
    instead of ~N_pad.  Used for forward (w = w_fwd) and, via the edge
    involution, for backward (h = ḡ, w = w_bwd) — same need sets both
    directions.
    """
    spec = P(axes, None)
    if send_idx is None:
        def body(h_l, w_l, s_l, r_l, pb_l, pc_l, pf_l):
            h_full = jax.lax.all_gather(h_l, axes, axis=0, tiled=True)
            msgs = w_l[0][:, None] * h_full[s_l[0]]
            return _local_segsum(msgs, r_l[0], pb_l[0], pc_l[0], pf_l[0],
                                 n_shard)

        return shard_map(
            body, mesh=mesh,
            in_specs=(spec,) * 7, out_specs=spec, check_vma=False,
        )(h, w, senders, recv, pb, pc, pf)

    ndev = _mesh_extent(mesh, axes)

    def body_halo(h_l, w_l, s_l, r_l, pb_l, pc_l, pf_l, si_l):
        halo = _halo_rows(h_l, si_l[0], axes, kind, dists, sizes, ndev)
        h_ext = jnp.concatenate([h_l, halo], axis=0)
        msgs = w_l[0][:, None] * h_ext[s_l[0]]
        return _local_segsum(msgs, r_l[0], pb_l[0], pc_l[0], pf_l[0],
                             n_shard)

    return shard_map(
        body_halo, mesh=mesh,
        in_specs=(spec,) * 8, out_specs=spec, check_vma=False,
    )(h, w, senders, recv, pb, pc, pf, send_idx)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _nsagg(mesh, axes, n_shard, halo_cfg, h, w_fwd, w_bwd, senders, recv,
           pb, pc, pf, send_idx):
    """out[r] = Σ_{e: recv_e = r} w_e · h[senders_e], node-sharded."""
    return _gather_aggregate(mesh, axes, n_shard, h, w_fwd,
                             senders, recv, pb, pc, pf, send_idx,
                             *halo_cfg)


def _nsagg_fwd(mesh, axes, n_shard, halo_cfg, h, w_fwd, w_bwd, senders,
               recv, pb, pc, pf, send_idx):
    out = _gather_aggregate(mesh, axes, n_shard, h, w_fwd,
                            senders, recv, pb, pc, pf, send_idx,
                            *halo_cfg)
    return out, (w_bwd, senders, recv, pb, pc, pf, send_idx)


def _nsagg_bwd(mesh, axes, n_shard, halo_cfg, res, g):
    w_bwd, senders, recv, pb, pc, pf, send_idx = res
    # dh[i] = Σ_{e: s_e = i} w_e ḡ[r_e]  =  Σ_{e: r_e = i} w_{π(e)} ḡ[s_e]
    # — the nn/scatter.py involution identity, which lands every term on
    # the shard that owns node i; so the backward is the same collective-
    # plus-local-CSR program as the forward with (ḡ, w_bwd) in place of
    # (h, w_fwd).  Weights are static (mean aggregation): no dw.
    dh = _gather_aggregate(mesh, axes, n_shard, g, w_bwd,
                           senders, recv, pb, pc, pf, send_idx,
                           *halo_cfg)
    return dh, None, None, None, None, None, None, None, None


_nsagg.defvjp(_nsagg_fwd, _nsagg_bwd)


def node_sharded_aggregate(h: jax.Array, g: NodeShardedGraph,
                           agg_dtype: Optional[Any] = None) -> jax.Array:
    """Mean-aggregate ``h`` over ``g``'s edges, node-sharded over
    ``g.axes``; returns [N_pad, F] in ``h``'s dtype (f32 accumulation).

    ``agg_dtype`` (e.g. bf16) casts the activations *before* the
    collective — halving the ICI bytes as well as the edge-gather HBM
    traffic, same contract as HGCConv's ``agg_dtype``.
    """
    out_dt = h.dtype
    if agg_dtype is not None:
        h = h.astype(agg_dtype)
    w_f = g.w_fwd.astype(h.dtype)
    w_b = g.w_bwd.astype(h.dtype)
    out = _nsagg(g.mesh, g.axes, g.n_shard,
                 (g.halo_kind, g.halo_dists, g.halo_sizes),
                 h, w_f, w_b, g.senders, g.recv, *g.plan,
                 g.send_idx if g.halo else None)
    return out.astype(out_dt)


def node_sharded_att_aggregate(
    h: jax.Array,        # [N_pad, F] node values (node-sharded)
    alpha_s: jax.Array,  # [N_pad] per-node sender attention scores
    alpha_r: jax.Array,  # [N_pad] per-node receiver attention scores
    g: NodeShardedGraph,
    agg_dtype: Optional[Any] = None,
    negative_slope: float = 0.2,
) -> jax.Array:
    """GAT-style segment-softmax aggregation, node-sharded.

    Receiver partitioning makes the softmax shard-local: every edge of a
    receiver lives on the shard that owns it, so the per-receiver
    max/sum run on local sorted segment ops.  Cross-shard reads are two
    all-gathers (h and the [N] sender-score vector); the backward is
    plain autodiff — all_gather transposes to psum_scatter and the edge
    gather to a per-shard scatter-add, so per-device work still scales
    ~1/ndev (with a worse constant than the mean path's involution
    backward; mean aggregation remains the optimized default).
    """
    out_dt = h.dtype
    mesh, axes, n_shard = g.mesh, g.axes, g.n_shard

    def _weights_and_agg(a_se, ar_l, r, mask, hs):
        from hyperspace_tpu.nn.gcn import bounded_att_logits

        # bounded-logit softmax (nn/gcn.py): exp is range-safe without a
        # per-receiver max pass — and stays numerically equivalent to the
        # single-device planned path (the equivalence tests rely on it)
        logits = bounded_att_logits(a_se + ar_l[r], negative_slope)
        w = jnp.where(mask, jnp.exp(logits), 0.0)
        if agg_dtype is not None:  # num and den see identically-rounded w
            hs = hs.astype(agg_dtype)
            w = w.astype(agg_dtype)
        acc_dt = jnp.promote_types(hs.dtype, jnp.float32)
        den = jax.ops.segment_sum(w.astype(acc_dt), r, n_shard,
                                  indices_are_sorted=True)
        num = jax.ops.segment_sum((w[:, None] * hs).astype(acc_dt), r,
                                  n_shard, indices_are_sorted=True)
        return (num / jnp.maximum(den, 1e-15)[:, None])

    def body(h_l, as_l, ar_l, senders, recv, w_f):
        h_full = jax.lax.all_gather(h_l, axes, axis=0, tiled=True)
        as_full = jax.lax.all_gather(as_l, axes, axis=0, tiled=True)
        s = senders[0]
        mask = w_f[0] > 0  # static edge-validity mask (padding has w=0)
        return _weights_and_agg(as_full[s], ar_l, recv[0], mask, h_full[s])

    def body_halo(h_l, as_l, ar_l, senders, recv, w_f, si_l):
        # halo layout (g.halo): senders are extended-local ids; α_s rides
        # as an extra feature column so the per-distance ppermutes serve
        # both the messages and the sender scores.  Plain autodiff: each
        # ppermute transposes to the reverse permutation + a local
        # scatter-add.
        s = senders[0]
        mask = w_f[0] > 0
        ha_l = jnp.concatenate([h_l, as_l[:, None].astype(h_l.dtype)], 1)
        halo_rows = _halo_rows(ha_l, si_l[0], axes, g.halo_kind,
                               g.halo_dists, g.halo_sizes,
                               _mesh_extent(mesh, axes))
        ha_ext = jnp.concatenate([ha_l, halo_rows], axis=0)
        picked = ha_ext[s]
        return _weights_and_agg(picked[:, -1], ar_l, recv[0], mask,
                                picked[:, :-1])

    spec = P(axes, None)
    vec = P(axes)
    if g.halo:
        out = shard_map(
            body_halo, mesh=mesh,
            in_specs=(spec, vec, vec, spec, spec, spec, spec),
            out_specs=spec, check_vma=False,
        )(h, alpha_s, alpha_r, g.senders, g.recv, g.w_fwd, g.send_idx)
    else:
        out = shard_map(
            body, mesh=mesh,
            in_specs=(spec, vec, vec, spec, spec, spec),
            out_specs=spec, check_vma=False,
        )(h, alpha_s, alpha_r, g.senders, g.recv, g.w_fwd)
    return out.astype(out_dt)


def pad_node_array(a: np.ndarray, n_pad: int, fill=0) -> np.ndarray:
    """Pad a per-node host array to the sharded node count ``n_pad``."""
    a = np.asarray(a)
    out = np.full((n_pad,) + a.shape[1:], fill, a.dtype)
    out[: a.shape[0]] = a
    return out
