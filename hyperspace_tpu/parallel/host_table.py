"""Beyond-HBM embedding tables: a host-resident master with a device
hot-row cache (ROADMAP item 3; docs/serving.md sizes the serve side,
this module is the TRAINING side).

One chip's HBM caps the in-HBM trainers at a few hundred thousand rows
(the largest benched table is 597k); the millions-of-users north star
needs tables that live in host DRAM and visit the device only as the
rows a chunk of steps actually touches.  The design:

- :class:`HostEmbedTable` — the master ``[N, W]`` table in host memory,
  stored as a LIST of row-range shards (never one monolithic array):
  cross-shard ``gather``/``write_back`` by id, a sharded Orbax
  save/restore that moves one shard at a time (restoring into a
  DIFFERENT shard count re-slices shard-by-shard — no full-table
  materialization on one host, instrumented by the
  ``host_table/io_rows_peak`` gauge), and a chunk iterator for
  streaming consumers (the scalable IVF builder, the synthetic
  big-table generator).
- :class:`DeviceHotCache` — a fixed-capacity device-resident ``[C, W]``
  row cache with a host-side id→slot map and chunk-granular LRU
  eviction.  ``ensure(ids)`` uploads only the MISSING rows (one
  bucketed ``device_put`` + one scatter per chunk — power-of-two
  bucketed so the executable count stays bounded), hands back the slot
  of every requested id, and leaves hits untouched: a row that stays
  hot across chunks never crosses the PCIe/ICI link again.  The
  training chunk program updates the cache array IN PLACE (donated);
  ``fetch(slots)`` reads rows back for the chunk-boundary write-back.

The trainer protocol (``train/host_embed.py``) per chunk: unique-id
union on host → ``ensure`` → run the planned-sparse chunk program over
the cache (plan indices remapped to cache slots) → ``fetch`` +
``write_back`` at the chunk boundary, so the master is current before
the next chunk's gather.  Synchronous gathers make the whole path
bitwise-identical to the in-HBM packed trainer (tested); the
``gather_ahead`` overlap mode relaxes that to a documented bounded
staleness (≤ prefetch_depth + 1 chunks — train/host_embed.py).

This module is the ONE sanctioned home of host-master → device
transfers: the ``full-table-materialization`` hyperlint rule errors on
``jax.device_put`` / ``jnp.asarray`` of a :class:`HostEmbedTable` (or
its shards) anywhere else — the table being host-resident is a
capacity INVARIANT, and one stray ``jnp.asarray(master.to_array())``
in a hot path would silently re-cap the design at HBM size.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.telemetry import registry as _telem

MANIFEST = "host_table.json"
FORMAT_VERSION = 1

# ensure()/fetch() pad their row counts to power-of-two buckets (floor
# at this) so the insert/gather executables stay one-per-bucket, not
# one-per-chunk — the serve batcher's compile contract applied to the
# cache maintenance programs
_MIN_BUCKET = 256


# largest single array save_sharded/load_sharded has moved this process
# (also surfaced as the host_table/io_rows_peak gauge): the "never
# materializes the full table on one host" invariant is testable as
# reset_io_peak(); <round trip>; io_rows_peak() <= N/shards (+ pad)
_io_rows_peak = 0


def io_rows_peak() -> int:
    return _io_rows_peak


def reset_io_peak() -> None:
    global _io_rows_peak
    _io_rows_peak = 0
    _telem.set_gauge("host_table/io_rows_peak", 0)  # hyperlint: disable=metric-unit-suffix — a peak ROW COUNT: the unit segment is mid-name, the suffix names the statistic


def _track_io_rows(rows: int) -> None:
    global _io_rows_peak
    if rows > _io_rows_peak:
        _io_rows_peak = rows
        _telem.set_gauge("host_table/io_rows_peak", rows)  # hyperlint: disable=metric-unit-suffix — a peak ROW COUNT: the unit segment is mid-name, the suffix names the statistic


def _shard_bounds(num_rows: int, shards: int) -> np.ndarray:
    """Row-range starts (len shards+1): near-equal contiguous ranges."""
    base, extra = divmod(num_rows, shards)
    sizes = [base + (1 if i < extra else 0) for i in range(shards)]
    return np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)


class HostEmbedTable:
    """Host-resident ``[N, W]`` master table as contiguous row shards."""

    def __init__(self, shards: Sequence[np.ndarray]):
        if not shards:
            raise ValueError("HostEmbedTable needs at least one shard")
        widths = {int(s.shape[1]) for s in shards}
        if len(widths) != 1:
            raise ValueError(f"shard widths differ: {sorted(widths)}")
        # writable host copies: np.asarray of a device array hands back
        # a READ-ONLY view, and the master must accept write_back
        self._shards = [
            s if isinstance(s, np.ndarray) and s.flags.writeable
            and s.flags.c_contiguous else np.array(s)
            for s in shards]
        self._starts = np.concatenate(
            [[0], np.cumsum([s.shape[0] for s in self._shards])]
        ).astype(np.int64)
        self.num_rows = int(self._starts[-1])
        self.width = widths.pop()
        self.dtype = self._shards[0].dtype
        # gather/write_back atomicity: the gather_ahead overlap mode
        # (train/host_embed.py) gathers from a PREFETCH thread while
        # the main thread writes the previous chunk back — without the
        # lock a row touched by both could be read mid-copy (half new,
        # half old: a vector that never existed at ANY step).  The lock
        # rounds that down to the documented whole-row bounded
        # staleness; its cost is one uncontended acquire per chunk-
        # granular bulk op, not per row
        self._lock = threading.Lock()

    # --- construction ---------------------------------------------------------

    @classmethod
    def from_array(cls, arr: np.ndarray, shards: int = 1) -> "HostEmbedTable":
        """Split an in-memory ``[N, W]`` array into ``shards`` row
        ranges (views — no copy; the table takes ownership)."""
        arr = np.asarray(arr)
        if arr.ndim != 2:
            raise ValueError(f"want [N, W]; got {arr.shape}")
        b = _shard_bounds(arr.shape[0], int(shards))
        return cls([arr[b[i]:b[i + 1]] for i in range(len(b) - 1)])

    @classmethod
    def build(cls, num_rows: int, width: int,
              fill: Callable[[int, int], np.ndarray], *,
              shard_rows: int = 1 << 20,
              dtype=np.float32) -> "HostEmbedTable":
        """Generate a table shard-by-shard: ``fill(start, rows)`` must
        return the ``[rows, width]`` block for that row range — the
        10M-row synthetic bench table is built this way, so no caller
        ever holds (or transfers) the whole table at once."""
        b = _shard_bounds(int(num_rows), max(1, -(-num_rows // shard_rows)))
        shards = []
        for i in range(len(b) - 1):
            rows = int(b[i + 1] - b[i])
            blk = np.asarray(fill(int(b[i]), rows), dtype)
            if blk.shape != (rows, width):
                raise ValueError(
                    f"fill({b[i]}, {rows}) returned {blk.shape}; "
                    f"want ({rows}, {width})")
            shards.append(blk)
        return cls(shards)

    # --- host-side access -----------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self._shards)

    def _locate(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        si = np.searchsorted(self._starts, ids, side="right") - 1
        return si, ids - self._starts[si]

    def gather(self, ids) -> np.ndarray:
        """``table[ids]`` across shards → a new ``[len(ids), W]`` array."""
        ids = np.asarray(ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_rows):
            raise ValueError(
                f"ids out of range [0, {self.num_rows}): "
                f"min={ids.min()}, max={ids.max()}")
        out = np.empty((len(ids), self.width), self.dtype)
        si, local = self._locate(ids)
        with self._lock:
            for s in np.unique(si):
                m = si == s
                out[m] = self._shards[s][local[m]]
        _telem.inc("host_table/gather_rows", int(len(ids)))
        return out

    def write_back(self, ids, rows: np.ndarray) -> None:
        """Scatter updated ``rows`` back into the master at ``ids``."""
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows)
        if rows.shape != (len(ids), self.width):
            raise ValueError(
                f"rows {rows.shape} must be ({len(ids)}, {self.width})")
        si, local = self._locate(ids)
        with self._lock:
            for s in np.unique(si):
                m = si == s
                self._shards[s][local[m]] = rows[m]
        _telem.inc("host_table/writeback_rows", int(len(ids)))

    def append_rows(self, rows: np.ndarray) -> np.ndarray:
        """Grow the table by ``rows`` ([M, W]) as a NEW trailing shard;
        returns the assigned ids ``[num_rows, num_rows + M)`` (int64).

        The live-index insert path (serve/delta.py): ids are row
        indices everywhere downstream, so new rows must land at the
        contiguous tail — existing shards, starts, and every id already
        handed out stay valid.  One appended shard per call keeps this
        O(M); compaction's full rebuild re-shards if fragmentation ever
        matters."""
        rows = np.asarray(rows, self.dtype)
        if rows.ndim != 2 or rows.shape[1] != self.width:
            raise ValueError(
                f"rows {rows.shape} must be [M, {self.width}]")
        if rows.shape[0] == 0:
            return np.empty((0,), np.int64)
        with self._lock:
            lo = self.num_rows
            self._shards.append(np.array(rows))
            self._starts = np.append(
                self._starts, lo + rows.shape[0]).astype(np.int64)
            self.num_rows = lo + rows.shape[0]
        _telem.inc("host_table/writeback_rows", int(rows.shape[0]))
        return np.arange(lo, lo + rows.shape[0], dtype=np.int64)

    def iter_chunks(self, chunk: int) -> Iterator[tuple[int, np.ndarray]]:
        """Yield ``(row_start, block)`` host views covering the table in
        order, each at most ``chunk`` rows and never crossing a shard
        boundary — the streaming consumers' read path (no copies)."""
        for s, arr in enumerate(self._shards):
            start = int(self._starts[s])
            for lo in range(0, arr.shape[0], chunk):
                yield start + lo, arr[lo:lo + chunk]

    def to_array(self) -> np.ndarray:
        """Materialize the FULL table on this host — tests and
        small-table eval only; never call this on a beyond-HBM path
        (the hyperlint rule flags device transfers of the result)."""
        return np.concatenate(self._shards, axis=0)

    # --- sharded Orbax save / restore ----------------------------------------

    def save_sharded(self, directory: str,
                     shards: Optional[int] = None) -> None:
        """Write the table as ``shards`` per-range Orbax items plus a
        JSON manifest.  Re-slicing to a different shard count than the
        in-memory layout streams one bounded block per saved shard —
        the largest array touched is max(in-memory shard, saved shard)
        rows (``host_table/io_rows_peak``)."""
        import orbax.checkpoint as ocp

        shards = int(shards or self.num_shards)
        if shards < 1:
            raise ValueError(f"shards must be >= 1; got {shards}")
        os.makedirs(directory, exist_ok=True)
        bounds = _shard_bounds(self.num_rows, shards)
        ck = _solo_checkpointer("host_table_save")
        for i in range(shards):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            blk = self._slice_rows(lo, hi)
            _track_io_rows(blk.shape[0])
            path = os.path.join(os.path.abspath(directory), f"shard_{i:05d}")
            ck.save(path, {"rows": blk}, force=True)
        ck.wait_until_finished()
        with open(os.path.join(directory, MANIFEST), "w",  # hyperlint: disable=multiprocess-unsafe-io — single-process API by contract; multihost callers go through save_owned_rows, whose manifest is process-0-gated
                  encoding="utf-8") as f:
            json.dump({
                "version": FORMAT_VERSION,
                "num_rows": self.num_rows, "width": self.width,
                "dtype": str(np.dtype(self.dtype)), "shards": shards,
                "bounds": [int(b) for b in bounds],
            }, f)

    def _slice_rows(self, lo: int, hi: int) -> np.ndarray:
        """Rows [lo, hi) as one array — a view when the range sits in
        one shard, a bounded copy when it straddles shards."""
        si = int(np.searchsorted(self._starts, lo, side="right") - 1)
        if hi <= self._starts[si + 1]:
            s0 = int(self._starts[si])
            return self._shards[si][lo - s0:hi - s0]
        return self.gather(np.arange(lo, hi, dtype=np.int64))

    @classmethod
    def load_sharded(cls, directory: str,
                     shards: Optional[int] = None) -> "HostEmbedTable":
        """Restore into ``shards`` row ranges (default: as saved).
        Every saved shard is read ONCE, in order, and copied into the
        overlapping destination shards — per-host array sizes stay
        bounded by max(saved shard, destination shard) rows whatever
        the two shard counts are."""
        import orbax.checkpoint as ocp

        with open(os.path.join(directory, MANIFEST), encoding="utf-8") as f:
            meta = json.load(f)
        if meta.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"unsupported host-table format {meta.get('version')!r}")
        n, w = int(meta["num_rows"]), int(meta["width"])
        dtype = np.dtype(meta["dtype"])
        saved = np.asarray(meta["bounds"], np.int64)
        new = _shard_bounds(n, int(shards or meta["shards"]))
        dest = [np.empty((int(new[i + 1] - new[i]), w), dtype)
                for i in range(len(new) - 1)]
        codec = meta.get("codec", "orbax")
        ck = None if codec == "npy" else _solo_checkpointer("host_table_load")
        for i in range(len(saved) - 1):
            lo, hi = int(saved[i]), int(saved[i + 1])
            blk = _read_shard(directory, i, codec, ck)
            _track_io_rows(blk.shape[0])
            # copy this saved range into every overlapping new shard
            for j in range(len(dest)):
                a, b = max(lo, int(new[j])), min(hi, int(new[j + 1]))
                if a < b:
                    dest[j][a - int(new[j]):b - int(new[j])] = \
                        blk[a - lo:b - lo]
            del blk
        return cls(dest)


def _read_shard(directory: str, i: int, codec: str, ck=None) -> np.ndarray:
    """One saved shard's rows, whichever codec wrote it: ``orbax``
    (``save_sharded``'s single-process item format) or ``npy``
    (``save_owned_rows``'s per-host format)."""
    if codec == "npy":
        return np.load(os.path.join(directory, f"shard_{i:05d}.npy"))
    if codec != "orbax":
        raise ValueError(f"unknown host-table codec {codec!r}")
    ck = ck or _solo_checkpointer("host_table_load")
    return ck.restore(
        os.path.join(os.path.abspath(directory), f"shard_{i:05d}"))["rows"]


def _solo_checkpointer(prefix: str):
    """A ``StandardCheckpointer`` whose coordination is scoped to THIS
    process.  Host-table shard items are per-process-private files —
    cross-host ordering belongs to the caller's barrier — so Orbax's
    default all-process barriers are never wanted here (and their
    device-collective implementation aborts on the CPU loopback
    backend).  Single-process behavior is unchanged."""
    import jax as _jax
    import orbax.checkpoint as ocp

    if _jax.process_count() == 1:
        return ocp.StandardCheckpointer()
    pi = _jax.process_index()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=pi, active_processes={pi},
            barrier_sync_key_prefix=f"{prefix}{pi}"))


def save_owned_rows(table: "HostEmbedTable", directory: str, *,
                    process_index: Optional[int] = None,
                    process_count: Optional[int] = None,
                    barrier: Optional[Callable[[], None]] = None) -> None:
    """Multi-process checkpoint of a host table: each process writes
    ONLY its owned row range (``multihost.process_row_range`` — one
    shard file per host, so checkpoint traffic scales with 1/n_hosts),
    then everyone meets at ``barrier()``, and process 0 ALONE writes
    the manifest.  The manifest is the commit point: a reader that
    races a crash mid-save finds shard files but no manifest and sees
    no checkpoint (``load_sharded`` raises), never a torn table.

    Shards are flat ``.npy`` files (fsync + atomic rename), NOT Orbax
    items: Orbax 0.7's numpy handler writes array data only on GLOBAL
    process 0 whatever ``MultiprocessingOptions`` scope it is given, so
    a per-host-private write path needs a per-host-private codec.  The
    manifest records ``codec: "npy"`` and keeps ``save_sharded``'s
    bounds contract, so :meth:`HostEmbedTable.load_sharded` restores it
    at ANY process/shard count — the PR 14 shard-count-elastic restore,
    lifted to hosts (a 2-host checkpoint restores bit-identically on
    1 host and vice versa; tested).
    """
    import jax as _jax

    pi = _jax.process_index() if process_index is None else int(process_index)
    pc = _jax.process_count() if process_count is None else int(process_count)
    if not 0 <= pi < pc:
        raise ValueError(f"process {pi} out of range [0, {pc})")
    os.makedirs(directory, exist_ok=True)
    bounds = _shard_bounds(table.num_rows, pc)
    lo, hi = int(bounds[pi]), int(bounds[pi + 1])
    blk = table._slice_rows(lo, hi)
    _track_io_rows(blk.shape[0])
    path = os.path.join(directory, f"shard_{pi:05d}.npy")
    tmp = f"{path}.tmp.{pi}"
    with open(tmp, "wb") as f:
        np.save(f, np.ascontiguousarray(blk))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # shard durable before it becomes visible
    if barrier is not None:
        barrier()  # every host's shard file is durable before commit
    if pi == 0:
        # the commit marker: written LAST, by process 0 only
        with open(os.path.join(directory, MANIFEST), "w",
                  encoding="utf-8") as f:
            json.dump({
                "version": FORMAT_VERSION, "codec": "npy",
                "num_rows": table.num_rows, "width": table.width,
                "dtype": str(np.dtype(table.dtype)), "shards": pc,
                "bounds": [int(b) for b in bounds],
            }, f)
    if barrier is not None:
        barrier()  # no host returns before the checkpoint is committed


def load_rows(directory: str, lo: int, hi: int) -> np.ndarray:
    """Rows ``[lo, hi)`` of a saved table, reading ONLY the overlapping
    shard items — the per-host restore path (each host re-reads just
    its owned range, whatever process count wrote the checkpoint)."""
    with open(os.path.join(directory, MANIFEST), encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported host-table format {meta.get('version')!r}")
    n, w = int(meta["num_rows"]), int(meta["width"])
    if not 0 <= lo <= hi <= n:
        raise ValueError(f"rows [{lo}, {hi}) out of range [0, {n}]")
    saved = np.asarray(meta["bounds"], np.int64)
    out = np.empty((hi - lo, w), np.dtype(meta["dtype"]))
    codec = meta.get("codec", "orbax")
    ck = None if codec == "npy" else _solo_checkpointer("host_table_load")
    for i in range(len(saved) - 1):
        slo, shi = int(saved[i]), int(saved[i + 1])
        a, b = max(lo, slo), min(hi, shi)
        if a >= b:
            continue
        if codec == "npy":
            # mmap: only the overlapping rows are ever read off disk
            blk = np.load(os.path.join(directory, f"shard_{i:05d}.npy"),
                          mmap_mode="r")
            _track_io_rows(b - a)
        else:
            blk = _read_shard(directory, i, codec, ck)
            _track_io_rows(blk.shape[0])
        out[a - lo:b - lo] = blk[a - slo:b - slo]
        del blk
    return out


def _next_bucket(n: int, cap: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return min(b, cap)


@jax.jit
def _cache_insert(cache: jax.Array, rows: jax.Array, slots: jax.Array):
    """Scatter uploaded rows into their cache slots (padded slots carry
    an out-of-range index and drop)."""
    return cache.at[slots].set(rows, mode="drop")


@jax.jit
def _cache_gather(cache: jax.Array, slots: jax.Array) -> jax.Array:
    return cache[jnp.minimum(slots, cache.shape[0] - 1)]


class DeviceHotCache:
    """Fixed-capacity device cache of hot master-table rows.

    ``capacity`` bounds the device footprint (``C × W`` elements); the
    id→slot map, LRU order and free list live on host.  Rows are
    uploaded on miss (``ensure``), read back for write-back (``fetch``),
    and updated in place by the training chunk program via the
    :attr:`array` property (hand the donated output back).

    Eviction is chunk-granular: ``ensure(ids)`` evicts
    least-recently-used ids NOT in ``ids`` when it needs slots.  The
    trainer writes every touched row back to the master at each chunk
    boundary, so an evicted row never holds the only copy of an update
    — eviction is free, and a cache hit means the device copy IS the
    master's current value.

    ``quant`` ("int8" | "int4") keeps the device copy PACKED: rows are
    quantized per-row on upload (``serve/quant.py`` — int8 code + f32
    scale, or two int4 nibbles per byte + f16 scale) and dequantized on
    ``fetch``, so the same HBM budget caches ~4×/~6× the hot rows — the
    serve-side read lane of the beyond-HBM story (docs/serving.md).  A
    packed cache is READ-ONLY from the device's point of view: the
    in-place training update via :attr:`array` is refused (training
    math needs f32 rows; re-quantizing per step would accumulate
    quantization error into the master).  PQ is deliberately NOT a
    cache lane — its codes only decode through whole-table-trained
    codebooks, which a row cache cannot retrain per upload.
    """

    def __init__(self, master: HostEmbedTable, capacity: int, *,
                 quant: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        if quant not in (None, "int8", "int4"):
            raise ValueError(
                f"cache quant must be None, 'int8' or 'int4'; got {quant!r}")
        self._master = master
        self.quant = quant
        self.capacity = int(min(capacity, master.num_rows))
        # sanctioned host→device transfer: the cache starts empty (the
        # zeros block is the cache's own buffer, not the master table)
        if quant == "int8":
            self._arr = jnp.zeros((self.capacity, master.width), jnp.int8)
            self._scale = jnp.zeros((self.capacity, 1), jnp.float32)
        elif quant == "int4":
            from hyperspace_tpu.serve.quant import int4_packed_width

            self._arr = jnp.zeros(
                (self.capacity, int4_packed_width(master.width)), jnp.uint8)
            self._scale = jnp.zeros((self.capacity, 1), jnp.float16)
        else:
            self._arr = jnp.zeros((self.capacity, master.width),
                                  jnp.dtype(master.dtype))
            self._scale = None
        # vectorized bookkeeping — at 100k-row working sets a per-id
        # Python dict walk WAS the host-resident step time (measured
        # ~20× the in-HBM step before this layout): id → slot (−1 =
        # absent), slot → id (−1 = free), and a per-slot chunk tick for
        # chunk-granular LRU
        self._slot_of = np.full(master.num_rows, -1, np.int32)
        self._slot_id = np.full(self.capacity, -1, np.int64)
        self._last_used = np.zeros(self.capacity, np.int64)
        self._tick = 0
        _telem.set_gauge("host_table/cache_capacity", self.capacity)

    @property
    def array(self) -> jax.Array:
        """The device cache — ``[C, W]`` rows (or the packed ``[C, ⌈W/2⌉]``
        nibbles / ``[C, W]`` int8 codes under ``quant``); hand to the
        chunk program (full-precision caches only)."""
        return self._arr

    @array.setter
    def array(self, new: jax.Array) -> None:
        if self.quant is not None:
            raise ValueError(
                f"a {self.quant} hot-row cache is a serve-side read lane; "
                "in-place training updates need a full-precision cache")
        if new.shape != (self.capacity, self._master.width):
            raise ValueError(
                f"cache array {new.shape} must be "
                f"({self.capacity}, {self._master.width})")
        self._arr = new

    @property
    def scale(self) -> Optional[jax.Array]:
        """Per-slot dequant scales ``[C, 1]`` (quantized caches only)."""
        return self._scale

    @property
    def nbytes(self) -> int:
        """Device bytes the cache holds resident — the capacity story
        a packed lane quarters."""
        n = self._arr.nbytes
        if self._scale is not None:
            n += self._scale.nbytes
        return n

    def ensure(self, ids: np.ndarray) -> np.ndarray:
        """Make every id resident; return its slot ([len(ids)] int32).

        ``ids`` must be UNIQUE (the trainer hands the chunk's unique-id
        union).  Misses are gathered from the master and uploaded as
        ONE power-of-two-bucketed transfer + scatter; hits cost a
        vectorized lookup.  Raises when ``ids`` alone exceed the
        capacity — a chunk's working set must fit, or ``hot_rows`` is
        undersized.
        """
        ids = self._check_ids(ids)
        miss = self._slot_of[ids] < 0
        rows = self._master.gather(ids[miss]) if miss.any() else None
        return self._ensure_rows(ids, rows)

    # split so the gather_ahead overlap mode (train/host_embed.py) can
    # hand PRE-FETCHED rows in — same insert path, stale by <= 1 chunk
    def ensure_with_rows(self, ids: np.ndarray, miss_rows,
                         miss_mask: np.ndarray) -> np.ndarray:
        """``ensure`` with the miss rows already gathered (the overlap
        mode's entry): ``miss_rows`` must align with ``miss_mask`` —
        positions of ``ids`` that were misses AT GATHER TIME.  Ids that
        became resident since are NOT overwritten (their cached value
        is at least as fresh, and re-inserting the stale gather would
        LOSE the newer value — so those rows are dropped)."""
        ids = self._check_ids(ids)
        still_miss = self._slot_of[ids] < 0
        keep = still_miss[miss_mask]  # rows whose id is still a miss
        rows = np.asarray(miss_rows)[keep] if miss_rows is not None else None
        return self._ensure_rows(ids, rows)

    def _check_ids(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if len(ids) > self.capacity:
            raise ValueError(
                f"chunk working set ({len(ids)} unique rows) exceeds the "
                f"hot-row cache capacity {self.capacity} — raise hot_rows= "
                "or lower chunk_steps/batch_size")
        if len(np.unique(ids)) != len(ids):
            raise ValueError("ensure() ids must be unique (pass the "
                             "chunk's unique-id union)")
        return ids

    def _ensure_rows(self, ids: np.ndarray,
                     miss_rows: Optional[np.ndarray]) -> np.ndarray:
        self._tick += 1
        slots = self._slot_of[ids].copy()
        miss = slots < 0
        self._last_used[slots[~miss]] = self._tick  # refresh hit recency
        nmiss = int(miss.sum())
        _telem.inc("host_table/cache_hits", len(ids) - nmiss)
        _telem.inc("host_table/cache_misses", nmiss)
        # cumulative hit-rate gauge (the serve cache_hit_rate idiom):
        # the level a dashboard — and the train plane's /metrics file —
        # reads directly without differencing the counters
        reg = _telem.default_registry()
        lookups = (reg.get("host_table/cache_hits")
                   + reg.get("host_table/cache_misses"))
        if lookups:
            _telem.set_gauge(
                "host_table/cache_hit_rate",
                round(reg.get("host_table/cache_hits") / lookups, 4))
        if not nmiss:
            return slots
        if miss_rows is None or len(miss_rows) != nmiss:
            raise ValueError(
                f"need {nmiss} miss rows; got "
                f"{0 if miss_rows is None else len(miss_rows)}")
        free = np.flatnonzero(self._slot_id < 0)
        if len(free) < nmiss:
            # evict least-recently-used slots OUTSIDE this request set
            # (this chunk's hits just got stamped with the new tick)
            need = nmiss - len(free)
            occ = np.flatnonzero((self._slot_id >= 0)
                                 & (self._last_used < self._tick))
            order = np.argsort(self._last_used[occ], kind="stable")[:need]
            evict = occ[order]
            self._slot_of[self._slot_id[evict]] = -1
            self._slot_id[evict] = -1
            _telem.inc("host_table/cache_evictions", need)
            free = np.concatenate([free, evict])
        mslots = free[:nmiss].astype(np.int32)
        miss_ids = ids[miss]
        self._slot_of[miss_ids] = mslots
        self._slot_id[mslots] = miss_ids
        self._last_used[mslots] = self._tick
        slots[miss] = mslots
        # ONE bucketed upload + scatter (pad slots out of range: drop);
        # packed lanes quantize per-row on host, so the link carries the
        # packed bytes, never the f32 rows
        scale_rows = None
        if self.quant == "int8":
            from hyperspace_tpu.serve.quant import quantize_rows

            miss_rows, scale_rows = quantize_rows(
                np.asarray(miss_rows, np.float32))
        elif self.quant == "int4":
            from hyperspace_tpu.serve.quant import pack_int4_rows

            miss_rows, scale_rows = pack_int4_rows(
                np.asarray(miss_rows, np.float32))
        b = _next_bucket(nmiss, self.capacity)
        rows_b = np.zeros((b,) + miss_rows.shape[1:], miss_rows.dtype)
        rows_b[:nmiss] = miss_rows
        slots_b = np.full(b, self.capacity, np.int32)
        slots_b[:nmiss] = mslots
        self._arr = _cache_insert(self._arr, jnp.asarray(rows_b),
                                  jnp.asarray(slots_b))
        sent = int(rows_b[:nmiss].nbytes)
        if scale_rows is not None:
            sc_b = np.zeros((b, 1), scale_rows.dtype)
            sc_b[:nmiss] = scale_rows
            self._scale = _cache_insert(self._scale, jnp.asarray(sc_b),
                                        jnp.asarray(slots_b))
            sent += int(sc_b[:nmiss].nbytes)
        _telem.inc("host_table/upload_rows", nmiss)
        _telem.inc("host_table/upload_bytes", sent)
        return slots

    def fetch(self, slots: np.ndarray) -> np.ndarray:
        """Read cache rows back to host (the chunk-boundary write-back
        read) — one bucketed device gather + one transfer.  Packed
        caches dequantize on host: the result is the f32 view of the
        resident codes (lossy vs the master — the read lane's
        contract, never a write-back source)."""
        slots = np.asarray(slots, np.int32)
        b = _next_bucket(len(slots), self.capacity)
        slots_b = np.zeros(b, np.int32)
        slots_b[:len(slots)] = slots
        out = np.asarray(_cache_gather(self._arr, jnp.asarray(slots_b)))
        if self.quant is not None:
            sc = np.asarray(_cache_gather(self._scale, jnp.asarray(slots_b)))
            if self.quant == "int8":
                from hyperspace_tpu.serve.quant import dequantize_rows

                out = dequantize_rows(out, sc)
            else:
                from hyperspace_tpu.serve.quant import dequantize_int4_rows

                out = dequantize_int4_rows(out, sc, self._master.width)
            out = out.astype(self._master.dtype)
        return out[:len(slots)]
