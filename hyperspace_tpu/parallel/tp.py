"""Tensor-parallel sharding rules (SURVEY.md §2 parallelism inventory, TP row).

The reference has no explicit TP evidence; SURVEY's plan is "provide via
GSPMD sharding rules" — on TPU that is precisely a `NamedSharding` rule
over the parameter pytree, after which XLA inserts the all-gathers /
reduce-scatters onto ICI.  The rule here is the standard Megatron-style
column split for 2-D kernels: every dense kernel's *output-feature* axis is
sharded over the ``model`` mesh axis, biases and everything 1-D stay
replicated.  Activations between layers are left to GSPMD, which keeps the
feature axis sharded through elementwise chains and re-gathers only where a
contraction needs it.

Used by `models/hgcn.py::make_sharded_step_*` (dp×tp HGCN training) and by
`__graft_entry__.dryrun_multichip`.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_names(path) -> list[str]:
    return [str(getattr(e, "key", getattr(e, "name", ""))) for e in path]


def tp_param_spec(path, leaf, axis: str = "model") -> P:
    """Partition spec for one parameter leaf under tensor parallelism:
    2-D dense kernels are column-sharded ``P(None, axis)``; scalars,
    biases, norms and manifold params (curvatures etc.) are replicated."""
    if "kernel" in _path_names(path) and getattr(leaf, "ndim", 0) == 2:
        return P(None, axis)
    return P()


def tp_param_shardings(params: Any, mesh: Mesh, axis: str = "model") -> Any:
    """Pytree of `NamedSharding`s for ``params`` under the TP rule.

    Degrades gracefully: if ``mesh`` has no ``axis`` (or it has size 1)
    everything is replicated, so callers can use one code path for
    dp-only, tp-only and dp×tp meshes.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        repl = NamedSharding(mesh, P())
        return jax.tree_util.tree_map(lambda _: repl, params)
    return jax.tree_util.tree_map_with_path(
        lambda p, l: NamedSharding(mesh, tp_param_spec(p, l, axis)), params)


def replicated_like(tree: Any, mesh: Mesh) -> Any:
    """Pytree of fully-replicated shardings matching ``tree``'s structure."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: repl, tree)


def state_shardings(state: Any, params: Any, mesh: Mesh,
                    axis: str = "model") -> Any:
    """Shardings for a whole train state, co-locating optimizer moments
    with their parameter shards (SURVEY.md §7 hard-part #4: Adam moments
    live in tangent spaces of moving points — their shards must sit with
    the parameter shards they transport).

    Optimizer states (optax) embed subtrees structurally mirroring
    ``params``, so a state leaf whose key-path *ends with* a parameter's
    full key-path (e.g. ``.0.mu.encoder.conv0.kernel`` vs
    ``encoder.conv0.kernel``) takes that parameter's TP spec; everything
    else (counts, PRNG keys, step counters) is replicated.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return replicated_like(state, mesh)

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    # longest-suffix-first so a param path that is itself a suffix of
    # another's can never shadow the longer match
    by_path = sorted(
        ((tuple(_path_names(p)), tp_param_spec(p, l, axis),
          getattr(l, "shape", ())) for p, l in flat),
        key=lambda kv: -len(kv[0]))

    def spec_for(path, leaf):
        names = tuple(_path_names(path))
        for ppath, spec, pshape in by_path:
            if len(names) >= len(ppath) and names[-len(ppath):] == ppath:
                # a state leaf only inherits the param's spec if its shape
                # is compatible — optax transforms may carry per-parameter
                # state of a different rank (e.g. scalars keyed by the
                # param name), which must fall back to replication
                return spec if getattr(leaf, "shape", ()) == pshape else P()
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda p, l: NamedSharding(mesh, spec_for(p, l)), state)
