"""Multi-host runtime: process-group init, host↔global array movement,
and the restart-from-checkpoint failure-recovery drill helpers
(SURVEY.md §2 "Multi-host DP" [B], §3.4, §5 "Failure detection").

The reference reaches multi-host scale through an NCCL/MPI process group
[B]; here the whole story is:

1. every process calls :func:`initialize` (one line — JAX's distributed
   runtime does discovery over the coordinator, Gloo/ICI do transport),
2. a mesh from :func:`hyperspace_tpu.parallel.mesh.multihost_mesh` puts
   the ``host`` axis on DCN and inner axes on ICI,
3. jitted programs move data with :func:`host_local_to_global` and read
   results with :func:`fetch_replicated`; Python never touches the wire.

Failure model (SURVEY.md §5): XLA programs are fixed-topology, so there
is no mid-step elasticity — a lost host aborts the program and recovery
is **restart-from-checkpoint**: every process re-runs the same script,
:func:`initialize` re-forms the group, and
:func:`hyperspace_tpu.train.checkpoint.CheckpointManager.restore` resumes
from the last saved step.  ``tests/parallel/test_multihost.py`` drills
exactly this: kill one loopback process mid-run, restart both, assert
the resumed run matches an uninterrupted one.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(
    coordinator: str,
    num_processes: int,
    process_id: int,
    *,
    local_device_count: Optional[int] = None,
) -> None:
    """Join the process group; call before any other JAX API.

    ``local_device_count`` forces N virtual CPU devices per process — the
    loopback test topology (SURVEY.md §4.6); leave None on real TPU hosts.
    """
    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_local_to_global(x, mesh: Mesh, spec: P):
    """Assemble per-host shards into one global array (data loading path:
    each host feeds only its own batch shard; no host sees the full array)."""
    return multihost_utils.host_local_array_to_global_array(x, mesh, spec)


def global_to_host_local(x, mesh: Mesh, spec: P):
    """Inverse of :func:`host_local_to_global` (eval/debug path)."""
    return multihost_utils.global_array_to_host_local_array(x, mesh, spec)


def fetch_replicated(x) -> np.ndarray:
    """Host copy of a replicated global array (loss/metrics).

    Raises on sharded input — returning one shard of a batch-sharded
    array as if it were the full value would corrupt metrics silently.
    """
    if hasattr(x, "addressable_shards"):
        if not x.is_fully_replicated:
            raise ValueError(
                f"fetch_replicated on a sharded array ({x.sharding}); "
                "use global_to_host_local for sharded values")
        return np.asarray(jax.device_get(x.addressable_shards[0].data))
    return np.asarray(jax.device_get(x))


def sync(name: str = "barrier") -> None:
    """Cross-host barrier (checkpoint commit points, shutdown)."""
    multihost_utils.sync_global_devices(name)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def assert_equal_across_hosts(x, msg: str = "") -> None:
    """Debug guard: all hosts must hold identical values (e.g. params
    after a DP step) — the multi-host analogue of a determinism check."""
    multihost_utils.assert_equal(x, fail_message=msg)


def gather_metric_exports(registry=None) -> list:
    """Every process's raw metric export, on every process.

    The multihost half of ``telemetry/aggregate.py``: each process
    JSON-encodes its ``Registry.export()`` tuple, the encoded payloads
    ride one ``process_allgather`` (zero-padded uint8 rows — allgather
    needs equal shapes, so a length field travels alongside), and every
    process decodes all of them.  ``merge_exports`` of the result is the
    fleet view; on one process this degenerates to ``[export_state()]``
    with no collective issued, so the serve/train wiring is identical
    for world_size 1 and N (the ISSUE 17 shape contract).
    """
    from hyperspace_tpu.telemetry import aggregate

    if jax.process_count() == 1:
        return [aggregate.export_state(registry)]
    payload = aggregate.encode_bytes(aggregate.export_state(registry))
    n = np.int32(len(payload))
    lens = np.asarray(multihost_utils.process_allgather(n))
    width = int(lens.max())
    row = np.zeros((width,), dtype=np.uint8)
    row[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    rows = np.asarray(multihost_utils.process_allgather(row))
    return [
        aggregate.decode_bytes(rows[i, : int(lens[i])].tobytes())
        for i in range(rows.shape[0])
    ]
