"""Multi-host runtime: process-group init, host↔global array movement,
and the restart-from-checkpoint failure-recovery drill helpers
(SURVEY.md §2 "Multi-host DP" [B], §3.4, §5 "Failure detection").

The reference reaches multi-host scale through an NCCL/MPI process group
[B]; here the whole story is:

1. every process calls :func:`initialize` (one line — JAX's distributed
   runtime does discovery over the coordinator, Gloo/ICI do transport),
2. a mesh from :func:`hyperspace_tpu.parallel.mesh.multihost_mesh` puts
   the ``host`` axis on DCN and inner axes on ICI,
3. jitted programs move data with :func:`host_local_to_global` and read
   results with :func:`fetch_replicated`; Python never touches the wire.

Failure model (SURVEY.md §5): XLA programs are fixed-topology, so there
is no mid-step elasticity — a lost host aborts the program and recovery
is **restart-from-checkpoint**: every process re-runs the same script,
:func:`initialize` re-forms the group, and
:func:`hyperspace_tpu.train.checkpoint.CheckpointManager.restore` resumes
from the last saved step.  ``tests/parallel/test_multihost.py`` drills
exactly this: kill one loopback process mid-run, restart both, assert
the resumed run matches an uninterrupted one.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(
    coordinator: str,
    num_processes: int,
    process_id: int,
    *,
    local_device_count: Optional[int] = None,
) -> None:
    """Join the process group; call before any other JAX API.

    ``local_device_count`` forces N virtual CPU devices per process — the
    loopback test topology (SURVEY.md §4.6); leave None on real TPU hosts.
    """
    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_primary() -> bool:
    """True on process 0 — the ONE process that writes shared artifacts
    (checkpoint manifests, serving exports, trend records).  Per-host
    outputs (shard files, local logs) go to per-host paths instead;
    everything else is gated on this (the multiprocess-unsafe-io rule,
    docs/multihost.md)."""
    return jax.process_index() == 0


def process_row_range(
    num_rows: int,
    index: Optional[int] = None,
    count: Optional[int] = None,
) -> tuple[int, int]:
    """This process's contiguous row range of a globally-owned
    ``num_rows`` — near-equal split, same convention as
    ``host_table._shard_bounds`` so per-host table shards and per-host
    batch shards agree.  Ranges over all processes are disjoint and
    cover ``[0, num_rows)`` (tested)."""
    index = jax.process_index() if index is None else int(index)
    count = jax.process_count() if count is None else int(count)
    if not 0 <= index < count:
        raise ValueError(f"process {index} out of range [0, {count})")
    base, extra = divmod(int(num_rows), count)
    lo = index * base + min(index, extra)
    return lo, lo + base + (1 if index < extra else 0)


def local_batch_rows(x, index: Optional[int] = None,
                     count: Optional[int] = None):
    """THIS host's leading-axis shard of a host-identical global batch
    (every process computes the same batch deterministically and keeps
    only its own rows — no cross-host data movement)."""
    lo, hi = process_row_range(np.shape(x)[0], index, count)
    return x[lo:hi]


def assemble_global_batch(local, mesh: Mesh):
    """Batch-sharded global array from per-host local rows.

    The data-plane closer: each host hands in only the rows it owns
    (``local_batch_rows`` of a host-identical batch, or rows it alone
    assembled) and gets back one global array sharded over the mesh's
    data-like axes.  Single-process this is a plain ``device_put`` with
    batch sharding — identical wiring either way."""
    from hyperspace_tpu.parallel.mesh import batch_sharding

    def one(a):
        sh = batch_sharding(mesh, np.ndim(a))
        if jax.process_count() == 1:
            return jax.device_put(a, sh)
        return multihost_utils.host_local_array_to_global_array(
            a, mesh, sh.spec)

    return jax.tree_util.tree_map(one, local)


def local_batch_shards(batch):
    """Per-leaf ``local_batch_rows`` over a host-identical batch pytree,
    with the equal-shard check ``host_local_array_to_global_array``
    needs: every leading axis must divide evenly across processes —
    batch builders pad to a mesh multiple first
    (``hgcn.round_up_pairs``)."""
    count = jax.process_count()

    def check(a):
        n = np.shape(a)[0]
        if n % count:
            raise ValueError(
                f"batch rows {n} not divisible by {count} processes — "
                "pad the batch to a mesh multiple first")
        return local_batch_rows(a)

    return jax.tree_util.tree_map(check, batch)


def distribute_batch(batch, mesh: Mesh):
    """Host-identical global batch → batch-sharded global array, feeding
    only this host's row range (the per-host data plane: host→device
    traffic scales with 1/n_hosts)."""
    return assemble_global_batch(local_batch_shards(batch), mesh)


def host_local_to_global(x, mesh: Mesh, spec: P):
    """Assemble per-host shards into one global array (data loading path:
    each host feeds only its own batch shard; no host sees the full array)."""
    return multihost_utils.host_local_array_to_global_array(x, mesh, spec)


def global_to_host_local(x, mesh: Mesh, spec: P):
    """Inverse of :func:`host_local_to_global` (eval/debug path)."""
    return multihost_utils.global_array_to_host_local_array(x, mesh, spec)


def fetch_replicated(x) -> np.ndarray:
    """Host copy of a replicated global array (loss/metrics).

    Raises on sharded input — returning one shard of a batch-sharded
    array as if it were the full value would corrupt metrics silently.
    """
    if hasattr(x, "addressable_shards"):
        if not x.is_fully_replicated:
            raise ValueError(
                f"fetch_replicated on a sharded array ({x.sharding}); "
                "use global_to_host_local for sharded values")
        return np.asarray(jax.device_get(x.addressable_shards[0].data))
    return np.asarray(jax.device_get(x))


# sync() barrier ids must be unique per use on the coordination service;
# per-name call counters keep them so (processes must call sync with the
# same names in the same order — true of any barrier discipline).
_SYNC_SEQ: dict[str, int] = {}
_SYNC_TIMEOUT_MS = 300_000


def sync(name: str = "barrier") -> None:
    """Cross-host barrier (checkpoint commit points, export gating).

    A HOST-side barrier: returns once every process has arrived — the
    right primitive for file-commit points, where the guarded effect
    (shard files durable before the manifest) happens in host code, not
    on device.  Rides the distributed coordination service when the
    process group is up, so it works on every backend — including the
    CPU loopback topology, whose backend cannot execute cross-process
    device collectives (``sync_global_devices`` aborts there).  Falls
    back to ``sync_global_devices`` if there is no coordination client,
    and is a no-op single-process.
    """
    if jax.process_count() == 1:
        return
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        client = None
    if client is None:
        multihost_utils.sync_global_devices(name)
        return
    seq = _SYNC_SEQ.get(name, 0)
    _SYNC_SEQ[name] = seq + 1
    client.wait_at_barrier(f"hyperspace_sync:{name}:{seq}",
                           _SYNC_TIMEOUT_MS)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def assert_equal_across_hosts(x, msg: str = "") -> None:
    """Debug guard: all hosts must hold identical values (e.g. params
    after a DP step) — the multi-host analogue of a determinism check.

    Rides a device collective (``broadcast_one_to_all``), which the CPU
    loopback backend does not implement — the loopback harnesses
    (``benchmarks/mh_worker.py``) exchange content digests through the
    shared filesystem behind a :func:`sync` barrier instead."""
    multihost_utils.assert_equal(x, fail_message=msg)


def gather_metric_exports(registry=None) -> list:
    """Every process's raw metric export, on every process.

    The multihost half of ``telemetry/aggregate.py``: each process
    JSON-encodes its ``Registry.export()`` tuple, the encoded payloads
    ride one ``process_allgather`` (zero-padded uint8 rows — allgather
    needs equal shapes, so a length field travels alongside), and every
    process decodes all of them.  ``merge_exports`` of the result is the
    fleet view; on one process this degenerates to ``[export_state()]``
    with no collective issued, so the serve/train wiring is identical
    for world_size 1 and N (the ISSUE 17 shape contract).
    """
    from hyperspace_tpu.telemetry import aggregate

    if jax.process_count() == 1:
        return [aggregate.export_state(registry)]
    payload = aggregate.encode_bytes(aggregate.export_state(registry))
    n = np.int32(len(payload))
    lens = np.asarray(multihost_utils.process_allgather(n))
    width = int(lens.max())
    row = np.zeros((width,), dtype=np.uint8)
    row[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    rows = np.asarray(multihost_utils.process_allgather(row))
    return [
        aggregate.decode_bytes(rows[i, : int(lens[i])].tobytes())
        for i in range(rows.shape[0])
    ]
