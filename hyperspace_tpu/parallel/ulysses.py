"""Ulysses (all-to-all) sequence parallelism for hyperbolic attention
(SURVEY.md §5 "Long-context / sequence parallelism" — the second of the
two first-class SP modes, complementing :mod:`hyperspace_tpu.parallel.ring`).

Layout: activations are sharded over the sequence axis between attention
calls (each device holds [B, H, L/n, D]).  Attention itself needs full
rows of the score matrix, so Ulysses trades the *sequence* sharding for a
*head* sharding exactly around the attention op with two ``all_to_all``
collectives:

    [B, H, L/n, D] --all_to_all(split H, concat L)--> [B, H/n, L, D]
        -> full-sequence Lorentz attention on H/n local heads
    [B, H/n, L, D] --all_to_all(split L, concat H)--> [B, H, L/n, D]

Communication: 2 × (B·H·L·D)/n per device per direction — constant in
sequence length per hop (vs ring's n hops), at the cost of requiring
H % n == 0.  On TPU the all_to_all rides the ICI torus; XLA overlaps it
with the surrounding compute where possible.

Both SP modes compute the same single-device attention math; since r04
the local op here is the N7 flash kernel
(:func:`hyperspace_tpu.kernels.attention.flash_attention` — flash in
both directions on TPU, dense twin elsewhere), so Ulysses long-context
memory stays per-block like the ring's.  Numerically interchangeable
with the ring and the dense form — the tests assert all three agree.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.kernels.attention import flash_attention
from hyperspace_tpu.parallel.mesh import shard_map


def ulysses_lorentz_attention(
    q: jax.Array,  # [B, H, L_local, D] this device's sequence shard
    k: jax.Array,
    v: jax.Array,
    manifold: Lorentz,
    axis_name: str,
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    k_mask: jax.Array | None = None,  # [B, L_local] bool key padding
) -> jax.Array:
    """Per-device body; call inside shard_map over ``axis_name``.

    Requires the head axis (dim 1) to be divisible by the axis size.
    """
    n = jax.lax.psum(1, axis_name)
    if q.shape[1] % n != 0:
        raise ValueError(
            f"Ulysses needs heads ({q.shape[1]}) divisible by axis size ({n})")
    # seq-sharded -> head-sharded: split heads, gather sequence
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name,
                  split_axis=1, concat_axis=2, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)        # [B, H/n, L, D]
    mask = None
    if k_mask is not None:
        # the head-sharded view sees the FULL sequence of keys — gather
        # the key-padding mask and broadcast over heads/queries
        mk = jax.lax.all_gather(k_mask, axis_name, axis=-1, tiled=True)
        mask = mk[:, None, None, :]  # [B, 1, 1, L]
    # the local attention is the N7 flash kernel (r04: flash in BOTH
    # directions on TPU, dense twin elsewhere) — with head sharding the
    # per-device score working set is already H/n tiles, and flash keeps
    # it per-BLOCK instead of per-sequence, so Ulysses long-context holds
    # forward and backward like the ring does
    out = flash_attention(qh, kh, vh, manifold.c, beta=beta, tau=tau,
                          mask=mask)
    # head-sharded -> seq-sharded: split sequence, gather heads
    return jax.lax.all_to_all(out, axis_name=axis_name,
                              split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention_sharded(
    q: jax.Array,  # [B, H, L, D] full arrays (sharded by the caller's specs)
    k: jax.Array,
    v: jax.Array,
    manifold: Lorentz,
    mesh: Mesh,
    axis: str = "seq",
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    k_mask: jax.Array | None = None,  # [B, L] bool key-padding mask
) -> jax.Array:
    """shard_map wrapper: shards the sequence axis (dim 2) over ``axis``.
    Omitting ``k_mask`` compiles the maskless path (no mask all_gather)."""
    spec = P(None, None, axis, None)

    if k_mask is None:
        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec)
        def run(q, k, v):
            return ulysses_lorentz_attention(q, k, v, manifold, axis,
                                             beta=beta, tau=tau)

        return run(q, k, v)

    @partial(shard_map, mesh=mesh,
             in_specs=(spec, spec, spec, P(None, axis)), out_specs=spec)
    def run(q, k, v, mk):
        return ulysses_lorentz_attention(q, k, v, manifold, axis,
                                         beta=beta, tau=tau, k_mask=mk)

    return run(q, k, v, k_mask)
