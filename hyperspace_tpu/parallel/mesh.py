"""Device-mesh construction and sharding helpers (SURVEY.md §2 N8).

The reference synchronizes gradients with NCCL all-reduce; here the same
role is played by GSPMD: arrays are placed with `NamedSharding`s over a
`jax.sharding.Mesh` and XLA compiles the `psum`s onto ICI (and onto DCN for
the host axis on multi-host meshes).  Axis conventions:

- ``data``  — batch/data parallelism (gradient all-reduce axis),
- ``model`` — tensor/embedding-row sharding,
- ``seq``   — sequence/context parallelism (ring attention),
- ``host``  — leading DCN axis on multi-host meshes (workload 5).

`jax.distributed.initialize` + a mesh spanning all hosts is the whole
multi-host story: Python never communicates across hosts, only XLA
collectives do (SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across the API move — the ONE place the name is
    resolved.  Newer jax exposes it as ``jax.shard_map`` (replication
    checking flag ``check_vma=``); 0.4.x has
    ``jax.experimental.shard_map.shard_map`` (same flag named
    ``check_rep=``).  ``check_vma=None`` means "library default" on
    either version."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pcast_varying(x, axis_name):
    """Mark ``x`` varying over ``axis_name`` inside a shard_map body —
    ``jax.lax.pcast(..., to="varying")`` on newer jax, ``jax.lax.pvary``
    where that's the spelling, and a no-op on 0.4.x, whose shard_map has
    no varying-axes typing to satisfy."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axis_name, to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, axis_name)
    return x


def make_mesh(
    axes: dict[str, int] | None = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh from {axis_name: size}; -1 = "fill with the rest".

    Defaults to pure data parallelism over all local devices.  For
    multi-host, pass an explicit ``host`` axis first so it maps onto DCN
    (mesh-major order = slowest-varying = cross-host).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    return Mesh(np.asarray(devices).reshape(sizes), tuple(names))


def multihost_mesh(axes: dict[str, int] | None = None) -> Mesh:
    """Mesh spanning all hosts: leading ``host`` axis over DCN, remaining
    axes over the local ICI topology (workload 5 [B])."""
    n_hosts = jax.process_count()
    per_host = jax.local_device_count()
    inner = axes or {"data": per_host}
    return make_mesh({"host": n_hosts, **inner})


def auto_mesh(multihost: bool = False, tp: int = 1) -> Optional[Mesh]:
    """Mesh selection shared by the CLI runners: the multi-host mesh when
    requested, a data(-×model) mesh over all local devices when there is
    more than one, else ``None`` (caller takes its single-device path)."""
    def warn_tp_dropped(n_avail):
        import warnings

        warnings.warn(
            f"auto_mesh: tp={tp} does not divide the {n_avail} available "
            "devices; falling back to pure data parallelism")

    if multihost:
        per_host = jax.local_device_count()
        if tp > 1 and per_host >= tp and per_host % tp == 0:
            # tp stays intra-host so its collectives ride ICI, not DCN
            return multihost_mesh({"data": per_host // tp, "model": tp})
        if tp > 1:
            warn_tp_dropped(per_host)
        return multihost_mesh()
    n = len(jax.devices())
    if n <= 1:
        return None
    if tp > 1 and n >= tp and n % tp == 0:
        return make_mesh({"data": n // tp, "model": tp})
    if tp > 1:
        warn_tp_dropped(n)
    return make_mesh({"data": n})


def model_mesh(shards: int = -1, *, devices: Optional[Sequence] = None
               ) -> Mesh:
    """A pure ``model``-axis mesh over ``shards`` devices (-1 = all) —
    the layout table-sharded *serving* uses (``serve/engine.py``;
    training meshes come from :func:`auto_mesh`).  ``shards`` larger
    than the device count, or 0, is an error — a silent clamp would
    quietly change the memory-per-chip story the caller sized for."""
    avail = list(devices if devices is not None else jax.local_devices())
    if shards == -1:
        shards = len(avail)
    if not 1 <= shards <= len(avail):
        raise ValueError(
            f"model_mesh: shards={shards} out of range [1, {len(avail)}] "
            "(-1 = all devices)")
    return make_mesh({"model": shards}, devices=avail[:shards])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) axis over every data-like mesh axis."""
    data_axes = tuple(a for a in ("host", "data") if a in mesh.axis_names)
    spec = (data_axes,) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def shard_batch(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Constrain an in-program value to batch sharding (GSPMD hint)."""
    return jax.lax.with_sharding_constraint(x, batch_sharding(mesh, x.ndim))


def data_extent(mesh: Mesh) -> int:
    """Total size of the data-like (batch-sharding) axes of ``mesh``."""
    return int(np.prod([mesh.shape[a] for a in ("host", "data")
                        if a in mesh.axis_names]))
