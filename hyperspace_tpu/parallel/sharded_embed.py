"""Row-sharded embedding tables: the TP/EP-like mode of SURVEY.md §2
("row-shard tables over ``model`` axis; shard_map + sparse gather for
lookups").

A [V, D] table too large for one chip is laid out P("model", None) —
each device owns a contiguous row range.  Lookup is a shard_map:

    every device gathers the requested rows it owns (others contribute
    zeros) and one ``psum`` over the model axis assembles full vectors.

Communication: one B×D all-reduce per lookup — independent of V, riding
ICI.  The VJP is the transpose: each device scatter-adds only the grad
rows it owns, with **no** cross-device traffic (the psum transposes to
an identity on the cotangent), so optimizer updates stay shard-local —
exactly the property that makes row sharding the right layout for
embedding training (the reference reaches the same place with NCCL
allgather/reduce-scatter pairs [INFERRED]).

The gather is exact under duplicate indices, and gradients under
duplicates accumulate (segment-combine), matching dense ``table[idx]``
semantics — asserted by tests/parallel/test_sharded_embed.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperspace_tpu.parallel.mesh import shard_map


def table_sharding(mesh: Mesh, axis: str = "model") -> NamedSharding:
    """Rows over ``axis``, features replicated."""
    return NamedSharding(mesh, P(axis, None))


def shard_table(table: jax.Array, mesh: Mesh, axis: str = "model") -> jax.Array:
    """Place a [V, D] table row-sharded (V must divide by the axis size)."""
    if table.shape[0] % mesh.shape[axis]:
        raise ValueError(
            f"table rows {table.shape[0]} not divisible by "
            f"{axis}={mesh.shape[axis]}")
    return jax.device_put(table, table_sharding(mesh, axis))


def local_gather(table_local: jax.Array, idx: jax.Array, n_rows: int,
                 axis: str):
    """Per-device body: gather owned rows, zeros elsewhere, psum.

    Public so other ``shard_map`` programs over a row-sharded table can
    assemble replicated rows inside their own bodies — the serve
    engine's sharded k-NN (``serve/engine.py``) gathers its query rows
    this way before scanning the local shard.

    Index semantics match dense ``table[idx]``: negatives wrap
    (idx + V) and out-of-range clamps to the last row — without this a
    valid-for-dense negative index would silently gather zeros.
    """
    idx = jnp.where(idx < 0, idx + n_rows, idx)
    idx = jnp.clip(idx, 0, n_rows - 1)
    rows = table_local.shape[0]
    lo = jax.lax.axis_index(axis) * rows
    local = idx - lo
    valid = (local >= 0) & (local < rows)
    safe = jnp.clip(local, 0, rows - 1)
    out = jnp.where(valid[..., None], table_local[safe], 0.0)
    return jax.lax.psum(out, axis)


def sharded_gather(
    table: jax.Array,  # [V, D], laid out P(axis, None)
    idx: jax.Array,    # [...] int32 indices into V (replicated)
    mesh: Mesh,
    axis: str = "model",
) -> jax.Array:
    """``table[idx]`` over a row-sharded table; differentiable w.r.t. table."""
    run = shard_map(
        partial(local_gather, n_rows=table.shape[0], axis=axis),
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
    )
    return run(table, idx)
