"""Parallelism: device meshes, sharding rules, ring + Ulysses sequence
parallelism.

The TPU-native replacement for the reference's NCCL backend (SURVEY.md §2
N8, §5 "Distributed comms backend"): XLA collectives over ICI/DCN under
GSPMD or shard_map — no hand-written transport.
"""

from hyperspace_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
from hyperspace_tpu.parallel.ring import ring_lorentz_attention  # noqa: F401
from hyperspace_tpu.parallel.ulysses import ulysses_lorentz_attention  # noqa: F401
