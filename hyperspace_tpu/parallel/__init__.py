"""Parallelism: device meshes, sharding rules, ring + Ulysses sequence
parallelism.

The TPU-native replacement for the reference's NCCL backend (SURVEY.md §2
N8, §5 "Distributed comms backend"): XLA collectives over ICI/DCN under
GSPMD or shard_map — no hand-written transport.
"""

from hyperspace_tpu.parallel.mesh import (  # noqa: F401
    auto_mesh,
    batch_sharding,
    data_extent,
    make_mesh,
    multihost_mesh,
    replicated,
    shard_batch,
)
from hyperspace_tpu.parallel.node_shard import (  # noqa: F401
    NodeShardedGraph,
    node_sharded_aggregate,
    node_sharded_att_aggregate,
    partition_graph,
    shard_graph,
)
from hyperspace_tpu.parallel.ring import (  # noqa: F401
    ring_attention_sharded,
    ring_lorentz_attention,
)
from hyperspace_tpu.parallel.tp import (  # noqa: F401
    state_shardings,
    tp_param_shardings,
)
from hyperspace_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention_sharded,
    ulysses_lorentz_attention,
)
