"""Persistent XLA compilation cache wiring (ROADMAP item 5, pillar 1).

Compile time is the dominant unmeasured cost in this stack: one short
run logged ``jax/recompiles=1532`` with 22.5 s of ``jax/compile_s``,
every serve (bucket, k, scan_mode, precision, nprobe) combination is a
fresh executable compiled on first hit, and the historical rc=124
bench/multichip artifact losses were compile-dominated.  Every one of
those compiles is deterministic — the same HLO on the same backend
produces the same executable — so a process restart re-paying them is
pure waste.  This module points JAX's on-disk compilation cache
(``jax_compilation_cache_dir``) at a persistent directory so run #2 of
anything deserializes executables instead of invoking XLA.

**Resolution order** (:func:`resolve_dir`): an explicit
``compile_cache_dir=`` flag wins; else the ``HYPERSPACE_COMPILE_CACHE``
env var; else the default ``<repo>/.cache/jax_compile`` beside the
graph-prep cache.  The cache is **on by default**; the value ``0`` (or
``false``/``no``/``off``) at either level disables it.  A directory
that cannot be created or written is a loud :class:`ValueError` (the
CLIs turn it into a clean usage exit) — a silently-dead cache would
re-create exactly the cold-start cliff this exists to kill.

**Cache-everything policy**: ``jax_persistent_cache_min_compile_time_
secs`` is set to 0 and the min-entry-size check is disabled, so even
the sub-second executables (the serve bucket ladder is made of them)
persist — disk is cheap next to a p99 cliff.

**Telemetry**: activation installs the shared ``jax.monitoring`` hook
(:func:`hyperspace_tpu.telemetry.registry.install_jax_monitoring_hook`),
which counts ``jax/compile_cache_hit`` (executables deserialized from
the cache — the backend compile never ran) and
``jax/compile_cache_miss`` (backend compiles while the cache was
enabled — each writes a new entry).  Both ride into every JSONL record,
``telemetry_summary``, and bench artifact through the existing
registry, so cache hit rates are visible for free
(docs/observability.md).

Wired into ``__graft_entry__.py``, ``cli/train.py``, ``cli/serve.py``
and ``bench.py`` — the four process entry points whose restarts pay
cold compiles.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_VAR = "HYPERSPACE_COMPILE_CACHE"
_OFF_VALUES = ("0", "false", "no", "off")

# activation state: the directory the cache was pointed at (None = not
# activated / disabled) plus the jax config value activation replaced
# (tests/conftest.py points the suite at its own cache — deactivate
# must restore it, not blank it).
_state: dict = {"dir": None, "prev": None}


def default_dir() -> str:
    """``<repo>/.cache/jax_compile`` — beside the graph-prep cache
    (``data/prep_cache.py``), under the checkout the artifacts live in."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(pkg), ".cache", "jax_compile")


def resolve_dir(flag: Optional[str] = None) -> Optional[str]:
    """The cache directory to use, or None when disabled.

    ``flag`` is the CLI's ``compile_cache_dir=`` value (None = not
    given); the env var covers flag-less entry points; the default is
    ON — persistent caching must not depend on every caller
    remembering a flag."""
    v = flag if flag not in (None, "") else os.environ.get(ENV_VAR, "")
    if v:
        return None if v.strip().lower() in _OFF_VALUES else v
    return default_dir()


def activate(flag: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at the resolved dir.

    Returns the directory in use, or None when disabled.  Raises
    :class:`ValueError` for a directory that cannot be created or
    written (callers map it to a clean usage error).  Idempotent —
    re-activating with the same resolution is a no-op; a different
    explicit dir re-points the cache (jax re-reads the config value
    per compile)."""
    d = resolve_dir(flag)
    if d is None:
        return None
    d = os.path.abspath(d)
    try:
        os.makedirs(d, exist_ok=True)
    except OSError as e:
        raise ValueError(
            f"compile_cache_dir={d!r}: cannot create the cache "
            f"directory ({e}) — fix the path or disable with "
            "compile_cache_dir=0") from None
    if not os.access(d, os.W_OK):
        raise ValueError(
            f"compile_cache_dir={d!r}: directory is not writable — "
            "fix permissions or disable with compile_cache_dir=0")
    import jax

    prev_cfg = jax.config.jax_compilation_cache_dir
    if _state["dir"] is None:
        _state["prev"] = prev_cfg
    jax.config.update("jax_compilation_cache_dir", d)
    if prev_cfg is not None and prev_cfg != d:
        # a cache was already configured (and possibly initialized) at
        # another dir in this process: drop the singleton so entries
        # actually land where the new config points
        _reset_jax_cache_object()
    # cache-everything policy (module docstring): the serve ladder is
    # made of sub-second executables, and those ARE the cold-start cost
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:
        pass  # older jax without the size gate: nothing to disable
    _state["dir"] = d
    # hit/miss counters ride the shared monitoring hook (idempotent)
    from hyperspace_tpu.telemetry import registry as telem

    telem.install_jax_monitoring_hook()
    return d


def is_enabled() -> bool:
    """Whether :func:`activate` pointed the cache somewhere this
    process — the registry hook's miss-attribution gate."""
    return _state["dir"] is not None


def cache_dir() -> Optional[str]:
    return _state["dir"]


def deactivate() -> None:
    """Restore the pre-activation cache config (tests: jax config is
    process-global — a test that activated must not leak its dir into
    the next, nor blank a cache the harness had already pointed)."""
    if _state["dir"] is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", _state["prev"])
    _reset_jax_cache_object()
    _state["dir"] = None
    _state["prev"] = None


def _reset_jax_cache_object() -> None:
    """Drop jax's in-process file-cache singleton: it is initialized
    once for the FIRST directory used, so re-pointing the config alone
    would silently keep writing to the old dir.  Private API —
    best-effort (a jax without it just keeps the first dir, which only
    in-process re-activation ever hits)."""
    try:
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except Exception:  # noqa: BLE001  # hyperlint: disable=swallow-base-exception — private-API drift: the first-activated dir keeps working, only an in-process re-point degrades
        pass
