"""Central mixed-precision policy — the ONE place bf16 is allowed in.

The TPU roofline the bench reports assumes the MXU's bf16 path
(197 Tflops bf16 vs 99 Tflops f32 on the reference chip), but hyperbolic
workloads are exactly where naive half precision breaks: the Poincaré
conformal factor 1/(1 − c‖x‖²) and every artanh/arcosh argument lose all
their information to bf16's 8-bit mantissa near the boundary (Nickel &
Kiela 2017; Chami et al. 2019 — the failure modes telemetry/health.py
monitors).  So the policy casts *selectively*, never globally:

==================  =========================================================
field               what runs in it
==================  =========================================================
``param``           master parameters / embedding tables (optimizer state
                    included — RAdam/RSGD moments are NEVER downcast)
``compute``         dense/conv/attention matmul inputs and activations —
                    the MXU-shaped Euclidean mass of a model
``accum``           reductions: losses, means, segment sums, metric sums
``boundary``        boundary-sensitive manifold math — exp/log/proj,
                    distances, conformal factors, hyperboloid time
                    coordinates — and anything feeding artanh/arcosh
==================  =========================================================

Presets::

    f32   param=f32  compute=f32   accum=f32  boundary=f32   (the default;
          every cast helper is the IDENTITY, so behavior is bit-identical
          to a build without this module)
    bf16  param=f32  compute=bf16  accum=f32  boundary=f32

Consumers never write ``jnp.bfloat16`` themselves — they take a policy
(usually from a config's ``precision: str`` field) and use the cast
helpers.  ``scripts/check_precision_policy.py`` lints the package for
ad-hoc bf16 literals outside this module and the kernel fast paths, so
casts can't bypass the policy.

Wiring map (docs/precision.md has the full table):

- models: HVAE conv/dense stacks and HyboNet's LorentzLinear matmuls run
  in ``compute``; HGCN maps ``precision=bf16`` onto its quality-validated
  ``agg_dtype``/``decoder_dtype`` bf16 message path; embedding-table
  workloads (poincare/product) are all-boundary, so their train step is
  documented f32 under every preset.
- train: ``train/loop.make_chunked_stepper(policy=...)`` casts explicit
  batch args to ``compute`` once per scanned chunk.
- serve: ``serve/engine.QueryEngine(precision="bf16")`` scans the table
  in bf16 and rescores the merged candidates in f32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

PRESET_NAMES = ("f32", "bf16")


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype assignment for one run.  Immutable and hashable, so it can
    ride in frozen model configs and jit static arguments."""

    name: str
    param: Any = jnp.float32
    compute: Any = jnp.float32
    accum: Any = jnp.float32
    boundary: Any = jnp.float32

    @property
    def mixed(self) -> bool:
        """True when the compute dtype differs from f32 — the ONLY case
        any cast helper does work (the f32 preset is the identity by
        construction, which is what makes ``precision=f32`` bit-identical
        to the pre-policy code)."""
        return jnp.dtype(self.compute) != jnp.dtype(jnp.float32)

    # --- cast helpers ---------------------------------------------------------
    # All helpers are identity for non-floating arrays (ids, masks) and
    # for the f32 preset; they return the input object unchanged whenever
    # no cast is needed, so the default path adds zero ops to the graph.

    def _cast(self, x, dt):
        if not self.mixed:
            return x
        x = jnp.asarray(x) if not hasattr(x, "dtype") else x
        if (jnp.issubdtype(x.dtype, jnp.floating)
                and x.dtype != jnp.dtype(dt)):
            return x.astype(dt)
        return x

    def cast_compute(self, x):
        """Activation/matmul-input cast (→ ``compute``)."""
        return self._cast(x, self.compute)

    def cast_boundary(self, x):
        """Manifold-op input cast (→ ``boundary``, f32 in every preset):
        call this where a compute-dtype activation is about to feed
        exp/log/proj/dist or any artanh/arcosh-shaped expression."""
        return self._cast(x, self.boundary)

    def cast_accum(self, x):
        """Reduction input cast (→ ``accum``)."""
        return self._cast(x, self.accum)

    def cast_param(self, x):
        """Master-parameter cast (→ ``param``)."""
        return self._cast(x, self.param)

    def cast_compute_tree(self, tree):
        """``cast_compute`` over every floating leaf of a pytree
        (integer/bool leaves — ids, masks — pass through untouched)."""
        if not self.mixed:
            return tree
        return jax.tree_util.tree_map(self.cast_compute, tree)

    def module_dtype(self):
        """The ``dtype=`` to hand a flax module: ``compute`` when mixed,
        ``None`` (flax's promote-inputs default) otherwise — passing an
        explicit f32 would be equivalent but None keeps the f32 preset
        textually identical to the pre-policy modules."""
        return self.compute if self.mixed else None


F32 = Policy("f32")
BF16 = Policy("bf16", compute=jnp.bfloat16)

_PRESETS = {"f32": F32, "bf16": BF16}


def get_policy(p: Union[None, str, Policy]) -> Policy:
    """Resolve ``None`` (→ f32), a preset name, or a Policy instance.

    Raises ``ValueError`` for unknown names — CLI layers turn that into
    a usage error listing the presets.
    """
    if p is None:
        return F32
    if isinstance(p, Policy):
        return p
    try:
        return _PRESETS[p]
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown precision {p!r} (want one of {PRESET_NAMES})"
        ) from None


def compute_matmul(x, w, compute_dtype=None):
    """``x @ w`` on the policy's compute lane: inputs cast to
    ``compute_dtype``, the product cast back to ``x.dtype`` so whatever
    follows (bias adds, time-coordinate reconstructions — the boundary
    lane) runs full-precision.  ``None`` is the plain matmul, untouched.
    The ONE home of this pattern — layer modules (``nn/layers.py``,
    ``nn/attention.py``) call it instead of hand-rolling the casts, so
    the contract can't drift between sites."""
    if compute_dtype is None:
        return x @ w
    return (x.astype(compute_dtype) @ w.astype(compute_dtype)).astype(
        x.dtype)


def parse_dtype(name: Union[str, Any, None], default: Any = None):
    """Map a CLI dtype string to the jnp dtype — the one sanctioned path
    from a flag like ``--agg-dtype bfloat16`` to an actual bf16 dtype
    (keeps ``jnp.bfloat16`` literals out of flag-parsing code, per the
    precision-policy lint)."""
    if name is None:
        return default
    if not isinstance(name, str):
        return name  # already a dtype
    try:
        return jnp.dtype(name)
    except TypeError:
        raise ValueError(f"unknown dtype name {name!r}") from None
