"""Shared support for the Pallas TPU kernel layer (SURVEY.md §2 N1-N8).

Three concerns live here:

1. **Dispatch** — every public kernel has a pure-JAX twin (the oracle).
   ``mode()`` decides per-call which implementation runs:
   ``pallas`` on a TPU backend, ``xla`` (the twin) elsewhere, overridable
   with ``HYPERSPACE_KERNELS={auto,pallas,interpret,xla}``.  ``interpret``
   runs the Pallas kernel through the interpreter on CPU — how the parity
   tests execute kernels without hardware (SURVEY.md §4.4).

2. **Mosaic-safe math** (``k*`` functions) — the kernels may only rely on
   transcendentals the Mosaic TPU compiler lowers robustly (exp/log/sqrt/
   tanh), so artanh/asinh/arcosh are spelled out in log/sqrt form with the
   same clamping policy as :mod:`hyperspace_tpu.manifolds.smath`.

3. **Tile padding** — TPU tiles are (8,128) f32; helpers pad row and lane
   dimensions with zeros.  All hyperbolic formulas used in the kernels are
   sums of products over the feature axis, so zero lanes are exact no-ops;
   zero rows are valid points (the origin) and get sliced off after.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_SUBLANE = 8
_LANE = 128

# Epsilon policy mirrors smath (kernels run f32 compute).
EPS_F32 = 1e-7
MIN_NORM_F32 = 1e-12
BALL_EPS_F32 = 4e-3
ARTANH_EPS_F32 = 3e-7


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` across the jax rename: newer jax calls it
    ``CompilerParams``, 0.4.x ``TPUCompilerParams`` — same fields either
    way (``dimension_semantics`` etc.).  Kernels must build against
    both, so this is the one place the name is resolved."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def mode() -> str:
    """Resolve the kernel implementation for the current call site."""
    m = os.environ.get("HYPERSPACE_KERNELS", "auto")
    if m not in ("auto", "pallas", "interpret", "xla"):
        raise ValueError(f"HYPERSPACE_KERNELS={m!r} (want auto|pallas|interpret|xla)")
    if m == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return m


def interpret_flag(m: str) -> bool:
    return m == "interpret"


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def pad_axis(x: jax.Array, axis: int, to: int) -> jax.Array:
    """Zero-pad ``axis`` of ``x`` up to a multiple of ``to``."""
    n = x.shape[axis]
    pad = round_up(n, to) - n
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def pad_rows_lanes(x: jax.Array, rows_to: int = _SUBLANE, lanes_to: int = _LANE) -> jax.Array:
    return pad_axis(pad_axis(x, -1, lanes_to), -2, rows_to)


VMEM_BUDGET = 4 * 1024 * 1024  # per-kernel working-set target (VMEM is ~16 MB)


def row_block(n_rows: int, dp: int = _LANE, n_bufs: int = 2, cap: int = 512) -> int:
    """Pick a row-block size under a VMEM budget.

    ``dp`` is the padded lane count and ``n_bufs`` the number of row-shaped
    VMEM buffers the kernel holds (inputs + output); the block shrinks for
    wide features so n_bufs × bn × dp × 4 B stays within VMEM_BUDGET
    (Pallas double-buffers blocks, hence the conservative target).
    """
    by_budget = VMEM_BUDGET // (4 * dp * max(n_bufs, 1))
    bn = max(_SUBLANE, (by_budget // _SUBLANE) * _SUBLANE)
    return min(round_up(n_rows, _SUBLANE), cap, bn)


def flatten_batch(x: jax.Array) -> tuple[jax.Array, tuple[int, ...]]:
    """[..., d] -> ([N, d], leading shape)."""
    lead = x.shape[:-1]
    return x.reshape((-1, x.shape[-1])), lead


def c_smem(c, dtype=jnp.float32) -> jax.Array:
    """Scalar curvature as the (1, 1) array SMEM wants (guide §Pitfall 8)."""
    return jnp.asarray(c, dtype).reshape(1, 1)


def dotT(a: jax.Array, b: jax.Array) -> jax.Array:
    """[n, k] × [m, k] → [n, m], contracting the last axis of both.

    HIGHEST precision: kernel matmuls feed arcosh/asinh-amplified quantities
    (distances, logits), where the default bf16-pass matmul costs ~1e-2
    absolute.  Also the rank-1 broadcast idiom: ``dotT(ones, col)`` turns a
    per-column [m, 1] quantity into [n, m] without a transpose/relayout.
    """
    return jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


# --- Mosaic-safe transcendentals (f32 in-kernel compute) ----------------------


def kasinh(x: jax.Array) -> jax.Array:
    """asinh via logs: sign(x)·log1p(|x| + |x|²/(1+sqrt(1+x²))), Mosaic-safe.

    The log1p form is exact for small |x| and never catastrophically
    cancels; callers bound |x| via their artanh-style clamps.
    """
    ax = jnp.abs(x)
    r = ksafe_sqrt(ax * ax + 1.0)
    return jnp.sign(x) * jnp.log1p(ax + ax * ax / (1.0 + r))


def ksafe_sqrt(x: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.maximum(x, 0.0))


def ksq_norm(x: jax.Array) -> jax.Array:
    return jnp.sum(x * x, axis=-1, keepdims=True)


def ksafe_norm(x: jax.Array) -> jax.Array:
    return ksafe_sqrt(ksq_norm(x))


def kartanh(x: jax.Array) -> jax.Array:
    """artanh via logs: 0.5*(log1p(x) - log1p(-x)), clamped inside (-1, 1)."""
    x = jnp.clip(x, -1.0 + ARTANH_EPS_F32, 1.0 - ARTANH_EPS_F32)
    return 0.5 * (jnp.log1p(x) - jnp.log1p(-x))


def ktanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(jnp.clip(x, -20.0, 20.0))


def karcosh1p(u: jax.Array) -> jax.Array:
    """arcosh(1+u), u >= 0: log1p(u + sqrt(u*(u+2))) (same form as smath)."""
    u = jnp.maximum(u, 0.0)
    return jnp.log1p(u + ksafe_sqrt(u * (u + 2.0)))


def ktanc(x: jax.Array) -> jax.Array:
    """tanh(x)/x, smooth at 0."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, 1.0, x)
    return jnp.where(small, 1.0 - x * x / 3.0, ktanh(xs) / xs)


def kartanc(x: jax.Array) -> jax.Array:
    """artanh(x)/x, smooth at 0."""
    small = jnp.abs(x) < 1e-3
    xs = jnp.where(small, 1.0, x)
    return jnp.where(small, 1.0 + x * x / 3.0, kartanh(xs) / xs)


def klambda_x(x: jax.Array, c) -> jax.Array:
    return 2.0 / jnp.maximum(1.0 - c * ksq_norm(x), EPS_F32)


def kproj(x: jax.Array, c) -> jax.Array:
    """Clamp points into the ball of curvature -c (mirrors PoincareBall.proj)."""
    sc = ksafe_sqrt(jnp.asarray(c))
    norm = jnp.maximum(ksafe_norm(x), MIN_NORM_F32)
    max_norm = (1.0 - BALL_EPS_F32) / jnp.maximum(sc, MIN_NORM_F32)
    return jnp.where(norm > max_norm, x / norm * max_norm, x)


def kmobius_add(x: jax.Array, y: jax.Array, c) -> jax.Array:
    x2 = ksq_norm(x)
    y2 = ksq_norm(y)
    xy = jnp.sum(x * y, axis=-1, keepdims=True)
    num = (1.0 + 2.0 * c * xy + c * y2) * x + (1.0 - c * x2) * y
    den = 1.0 + 2.0 * c * xy + (c * c) * x2 * y2
    return num / jnp.maximum(den, EPS_F32)


def kgyration(u: jax.Array, v: jax.Array, w: jax.Array, c) -> jax.Array:
    u2 = ksq_norm(u)
    v2 = ksq_norm(v)
    uv = jnp.sum(u * v, axis=-1, keepdims=True)
    uw = jnp.sum(u * w, axis=-1, keepdims=True)
    vw = jnp.sum(v * w, axis=-1, keepdims=True)
    c2 = c * c
    a = -c2 * uw * v2 + c * vw + 2.0 * c2 * uv * vw
    b = -c2 * vw * u2 - c * uw
    d = 1.0 + 2.0 * c * uv + c2 * u2 * v2
    return w + 2.0 * (a * u + b * v) / jnp.maximum(d, EPS_F32)
