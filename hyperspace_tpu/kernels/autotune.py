"""Empirical tile autotuner for the fused scan-top-k kernel.

``kernels/scan_topk.py`` sizes its streamed table tile (``bm``) with a
static VMEM-footprint model (:func:`~hyperspace_tpu.kernels.scan_topk.
fused_tile_rows`) — a conservative guess at what fits, not a
measurement of what is fast.  The real optimum depends on the backend's
memory system (VMEM banking, DMA granularity, the CPU twin's loop
overhead), which no model on this image can predict.  This module
closes the loop empirically:

- :func:`measure` times :func:`scan_topk` / :func:`scan_topk_cand` on
  the **real backend** over candidate ``bm`` tiles (powers of two on
  the 128 grid, capped by the static footprint model so nothing a real
  chip's Mosaic would reject is ever timed or stored), per
  ``(variant, dim, dtype, k)``;
- :func:`save_table` persists the winners as a **versioned JSON table**
  keyed ``(variant, dim, dtype, k, device_kind)`` —
  ``configs/scan_topk_tiles.json`` by default,
  ``HYPERSPACE_AUTOTUNE_TABLE`` overrides (``0`` disables lookups);
- :func:`lookup` is the hot-path read ``fused_tile_rows`` /
  ``fused_cand_tile_rows`` consult: a tuned entry for the current
  device kind wins, anything else — no table, version mismatch,
  foreign device kind, an entry off the 128 grid — falls back to the
  static model.  **Fallback is always silent and always safe**: tile
  choice is result-invisible (the kernel's merge extracts exact copies
  with global-column tie-breaks, so every tile size produces bitwise
  identical results — tested), so a stale or missing table can cost
  only speed, never correctness.

``scripts/autotune_scan_topk.py`` is the offline driver (run it once
per device kind; the table is additive — entries for other device
kinds are preserved).  Format and fallback rules: docs/kernels.md
"Autotuned tiles".
"""

from __future__ import annotations

import json
import os
from typing import Optional

TABLE_VERSION = 1
ENV_TABLE = "HYPERSPACE_AUTOTUNE_TABLE"
_OFF_VALUES = ("0", "false", "no", "off")

# candidate streamed-tile heights: the 128-grid powers of two the
# schedule accepts; measure() intersects with the static footprint cap
CANDIDATE_BM = (128, 256, 512, 1024)
VARIANTS = ("slab", "cand")

# in-process table cache: {abs path: entries dict}; reset_cache() for
# tests.  Loaded once per path — lookup sits on the engine-build path.
_cache: dict = {}


def default_table_path() -> str:
    """``<repo>/configs/scan_topk_tiles.json`` — beside the run
    configs, so a tuned table ships with a deployment checkout."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "configs",
                        "scan_topk_tiles.json")


def table_path() -> Optional[str]:
    """The table to consult (None = lookups disabled via env ``0``)."""
    v = os.environ.get(ENV_TABLE, "")
    if v:
        return None if v.strip().lower() in _OFF_VALUES else v
    return default_table_path()


def _dtype_name(dtype) -> str:
    import jax.numpy as jnp

    return jnp.dtype(dtype).name


def entry_key(variant: str, dim: int, dtype, k: int,
              device_kind: str) -> str:
    """The table's flat entry key."""
    return f"{variant}|{int(dim)}|{_dtype_name(dtype)}|{int(k)}|{device_kind}"


def device_kind() -> str:
    """The current backend's device kind (e.g. ``cpu``,
    ``TPU v5e``) — resolved lazily; callers only ask once a table with
    entries exists, so a pure sizing call never initializes a backend."""
    import jax

    return str(jax.devices()[0].device_kind)


def _valid_bm(bm) -> Optional[int]:
    """A stored tile is used only if it is a positive multiple of 128
    within the schedule's range — anything else is a corrupt/foreign
    entry and falls back to the static model."""
    if isinstance(bm, bool) or not isinstance(bm, int):
        return None
    if bm < 128 or bm > 4096 or bm % 128:
        return None
    return bm


def load_table(path: str) -> dict:
    """{entry key: entry dict} from a table file; empty on any problem
    (missing file, unparseable JSON, version mismatch) — the fallback
    rule (module docstring)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return {}
    if not isinstance(doc, dict) or doc.get("version") != TABLE_VERSION:
        return {}
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


def save_table(entries: dict, path: str) -> None:
    """Write the versioned table (atomic-ish: tmp + rename, so a reader
    never sees a half-written JSON)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": TABLE_VERSION, "entries": entries},
                  f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def reset_cache() -> None:
    """Drop the in-process table cache (tests; after a fresh tune)."""
    _cache.clear()


def lookup(variant: str, dim: int, dtype, k: int) -> Optional[int]:
    """The tuned ``bm`` for this shape on the CURRENT device kind, or
    None (→ the caller's static model).  Cheap: the table file is read
    once per process per path, and the backend is only queried when
    the table actually has entries."""
    path = table_path()
    if path is None:
        return None
    entries = _cache.get(path)
    if entries is None:
        entries = _cache[path] = load_table(path)
    if not entries:
        return None
    e = entries.get(entry_key(variant, dim, dtype, k, device_kind()))
    if not isinstance(e, dict):
        return None
    return _valid_bm(e.get("bm"))


# --- offline measurement ------------------------------------------------------


def _candidates(variant: str, dim: int, dtype, k: int) -> list[int]:
    """CANDIDATE_BM capped by the static footprint model — a tile the
    model rejects would only compile on the CPU twin (Mosaic would
    refuse it on a real chip), so it is never timed or stored."""
    from hyperspace_tpu.kernels import scan_topk as K

    # allow_tuned=False: the cap must come from the STATIC model — a
    # previously-tuned small tile must never shrink the search space of
    # the next tune (the table would self-lock at its first answer)
    cap = (K.fused_tile_rows(dim, dtype, k, allow_tuned=False)
           if variant == "slab"
           else K.fused_cand_tile_rows(dim, dtype, k, allow_tuned=False))
    out = [bm for bm in CANDIDATE_BM if bm <= cap]
    return out or [128]


def measure(variant: str, dim: int, dtype, k: int, *,
            rows: int = 65_536, batch: int = 256, cand: int = 512,
            repeats: int = 3, candidates=None, seed: int = 0) -> dict:
    """Time the kernel over candidate tiles on the real backend.

    Returns ``{"bm": best, "ms": best_ms, "timings": {bm: ms}}`` —
    min-of-``repeats`` wall-clock per candidate after one warm
    (compile) call, on a synthetic Poincaré slab shaped like the serve
    workload.  ``variant="cand"`` times the per-query candidate scorer
    over ``cand`` gathered ids per row instead of the shared slab."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.kernels import scan_topk as K

    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}; got {variant!r}")
    rng = np.random.default_rng(seed)
    spec = ("poincare", 1.0)
    table = np.tanh(rng.standard_normal((rows, dim)) * 0.3).astype(
        np.float32) * 0.7
    slab = jnp.asarray(table, jnp.dtype(dtype))
    q_rows = jnp.asarray(table[: batch], jnp.float32)
    q_idx = jnp.arange(batch, dtype=jnp.int32)
    if variant == "cand":
        cand_ids = jnp.asarray(
            rng.integers(0, rows, size=(batch, cand)), jnp.int32)

    def run(bm: int):
        if variant == "slab":
            return K.scan_topk(slab, q_rows.astype(slab.dtype), q_idx, 0,
                               spec=spec, k=k, n=rows, tile_rows=bm)
        return K.scan_topk_cand(slab, cand_ids,
                                q_rows.astype(slab.dtype), q_idx,
                                spec=spec, k=k, tile_rows=bm)

    timings: dict[int, float] = {}
    for bm in (candidates or _candidates(variant, dim, dtype, k)):
        out = run(bm)  # compile + warm
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            out = run(bm)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        timings[bm] = round(best * 1e3, 4)
    best_bm = min(timings, key=timings.get)
    return {"bm": best_bm, "ms": timings[best_bm], "timings": timings}


def autotune(dims, dtypes, ks, *, variants=VARIANTS, rows: int = 65_536,
             batch: int = 256, repeats: int = 3,
             base_entries: Optional[dict] = None,
             log=print) -> dict:
    """Measure a grid and return the merged entries dict (existing
    entries — other device kinds, other shapes — are preserved; the
    grid's keys are overwritten with fresh measurements)."""
    kind = device_kind()
    entries = dict(base_entries or {})
    for variant in variants:
        for dim in dims:
            for dtype in dtypes:
                for k in ks:
                    m = measure(variant, dim, dtype, k, rows=rows,
                                batch=batch, repeats=repeats)
                    key = entry_key(variant, dim, dtype, k, kind)
                    entries[key] = {
                        "variant": variant, "dim": int(dim),
                        "dtype": _dtype_name(dtype), "k": int(k),
                        "device_kind": kind, "bm": m["bm"],
                        "ms": m["ms"],
                        "timings": {str(b): t
                                    for b, t in m["timings"].items()},
                    }
                    log(f"[autotune] {key}: bm={m['bm']} "
                        f"({m['ms']} ms; {m['timings']})")
    return entries
