"""Block-CSR segment-sum Pallas kernel — scatter as MXU matmul.

XLA's scatter-add lowers to a serialized row-by-row update on TPU: at
ogbn-arxiv scale (2.4 M × 128 f32 edge values into 169 k node rows) a
single ``segment_sum`` costs ~0.8–1.7 s on a v5e chip while the matching
gather is 28 ms.  Since every aggregation in this framework runs over a
**receiver-sorted** edge list (``data.graphs.prepare``), each node block's
incoming edges form a contiguous chunk range, and the scatter becomes a
sum of one-hot matmuls — MXU work instead of serialized stores
(SURVEY.md §7 hard-part #3; the reference's CUDA backend leans on
atomics for the same aggregation [INFERRED], which TPUs do not have):

    out[i·bn : (i+1)·bn]  =  Σ_chunks  onehot(recv_chunk − i·bn) @ vals_chunk

A host-side *plan* (``build_csr_plan``) flattens (node-block, edge-chunk)
pairs into one grid of work items so hub nodes cost exactly their edge
count — no per-block padding to the max degree.  Consecutive items share
an output block; Pallas keeps it resident in VMEM and the kernel zeroes
it on each block's first item (standard revisiting-reduction pattern).

Boundary chunks shared by two node blocks are loaded by both and masked
by the one-hot range test (local index outside [0, bn) matches nothing),
so total DMA is E + O(#blocks) chunk loads.  Measured at arxiv scale:
0.83 s (XLA sorted scatter) → ~8 ms.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S

_BN = 128  # nodes per output block (sublane-tiled)
_BK = 512  # edges per chunk (grid-step amortization vs VMEM)


class CsrPlan(NamedTuple):
    """Work-item schedule for :func:`csr_segment_sum` (host-built, static).

    All three arrays have shape [T] (T = total work items); they ride
    through jit as ordinary int32 device arrays — only their *shape* is
    baked into the compiled program.
    """

    block: np.ndarray  # item -> output node-block index
    chunk: np.ndarray  # item -> edge-chunk index
    first: np.ndarray  # 1 iff item is the first of its node block


def build_csr_plan(
    receivers: np.ndarray, num_nodes: int, bn: int = _BN, bk: int = _BK
) -> CsrPlan:
    """Plan the (node-block × edge-chunk) work items for a sorted edge list.

    ``receivers`` must be ascending (``data.graphs.prepare`` guarantees
    it); padding edges point at ``num_nodes - 1`` and carry zero values,
    so they are inert wherever they land.
    """
    r = np.asarray(receivers)
    if len(r) > 1 and not np.all(np.diff(r) >= 0):
        raise ValueError("build_csr_plan requires receiver-sorted edges")
    e_pad = S.round_up(max(len(r), 1), bk)
    nb = -(-num_nodes // bn)
    nchunks = e_pad // bk
    # rowptr over *block* boundaries only — that is all the kernel needs
    starts = np.searchsorted(r, np.arange(nb) * bn, side="left")
    ends = np.searchsorted(r, np.minimum(np.arange(1, nb + 1) * bn, num_nodes),
                           side="left")
    # every block gets ≥1 item (so its output is zeroed), and all chunk
    # indices stay in [0, nchunks): an empty trailing block whose edge
    # range starts at exactly len(r) == e_pad must not index one past the
    # end, so clamp c0 first and apply the upper clamp last
    c0 = np.minimum(starts // bk, nchunks - 1)
    c1 = np.clip(-(-ends // bk), c0 + 1, nchunks)
    counts = c1 - c0
    t = int(counts.sum())
    block = np.repeat(np.arange(nb, dtype=np.int32), counts)
    chunk = (np.arange(t, dtype=np.int32)
             - np.repeat(np.cumsum(counts) - counts, counts)
             + np.repeat(c0, counts)).astype(np.int32)
    first = np.zeros(t, np.int32)
    first[np.cumsum(counts) - counts] = 1
    return CsrPlan(block=block, chunk=chunk.astype(np.int32), first=first)


def _body(bn: int):
    def body(blk_ref, chk_ref, first_ref, recv_ref, vals_ref, o_ref):
        t = pl.program_id(0)
        b = blk_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        recv = recv_ref[0]                       # [bk//128, 128] int32
        local = recv - b * bn
        acc = jnp.zeros_like(o_ref[:], jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        # 128-edge sub-chunks: one-hot [bn, 128] @ vals [128, dp] on the MXU
        for j in range(recv.shape[0]):
            oh = (rows == local[j : j + 1, :]).astype(jnp.float32)
            vals = vals_ref[j * 128 : (j + 1) * 128, :].astype(jnp.float32)
            # HIGHEST: 0/1 one-hot times f32 is an exact selection under the
            # 3-pass bf16 decomposition; default single-pass costs ~1e-3 rel
            acc += jnp.dot(oh, vals, preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGHEST)
        o_ref[:] += acc

    return body


def _pallas_csr(vals, recv2d, plan_arrays, num_nodes, bn, bk, interpret):
    t = plan_arrays[0].shape[0]
    n_pad = S.round_up(num_nodes, bn)
    dp = vals.shape[-1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((bk, dp), lambda t, blk, chk, first: (chk[t], 0)),
        ],
        out_specs=pl.BlockSpec((bn, dp), lambda t, blk, chk, first: (blk[t], 0)),
    )
    out = pl.pallas_call(
        _body(bn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, dp), jnp.float32),
        interpret=interpret,
    )(*plan_arrays, recv2d, vals)
    return out


def csr_segment_sum(
    values: jax.Array,     # [E, F] edge values (zero on padding edges)
    receivers: jax.Array,  # [E] int32, sorted ascending
    plan: tuple,           # CsrPlan as device arrays (block, chunk, first)
    num_segments: int,
) -> jax.Array:
    """``segment_sum(values, receivers)`` via block-CSR one-hot matmuls.

    Twin/oracle: ``jax.ops.segment_sum(..., indices_are_sorted=True)``.
    The plan must have been built from the same (sorted) receivers with
    :func:`build_csr_plan`.
    """
    m = S.mode()
    if m == "xla":
        # same accumulate-in-≥f32 semantics as the kernel (f64 stays f64)
        acc_dt = jnp.promote_types(values.dtype, jnp.float32)
        acc = jax.ops.segment_sum(values.astype(acc_dt), receivers,
                                  num_segments, indices_are_sorted=True)
        return acc.astype(values.dtype)
    e, f = values.shape
    bn, bk = _BN, _BK
    dp = S.round_up(f, 128)
    e_pad = S.round_up(e, bk)
    vals = S.pad_axis(S.pad_axis(values, -1, 128), 0, bk)
    recv2d = S.pad_axis(receivers, 0, bk).reshape(e_pad // bk, bk // 128, 128)
    out = _pallas_csr(vals, recv2d, tuple(plan), num_segments, bn, bk,
                      S.interpret_flag(m))
    return out[:num_segments, :f].astype(values.dtype)


# --- scalar (per-edge) segment reductions -------------------------------------


NEG_FILL = -3.0e38  # f32-safe -inf stand-in (finite so max-accumulate stays exact;
# nn.gcn imports it for the matching empty-segment threshold)


def _body_1d(bn: int, op: str):
    init = 0.0 if op == "sum" else NEG_FILL

    def body(blk_ref, chk_ref, first_ref, recv_ref, vals_ref, o_ref):
        t = pl.program_id(0)
        b = blk_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.full_like(o_ref, init)

        recv = recv_ref[0]                        # [bk//128, 128] int32
        vals = vals_ref[0].astype(jnp.float32)    # [bk//128, 128]
        local = recv - b * bn
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        acc = o_ref[:]
        # lane-partial accumulation: each 128-edge sub-chunk contributes a
        # [bn, 128] select; the per-row combine over lanes happens once,
        # outside the kernel (an XLA row reduction of [n_pad, 128])
        for j in range(recv.shape[0]):
            sel = jnp.where(rows == local[j : j + 1, :],
                            jnp.broadcast_to(vals[j : j + 1, :], (bn, 128)),
                            init)
            acc = acc + sel if op == "sum" else jnp.maximum(acc, sel)
        o_ref[:] = acc

    return body


def csr_segment_reduce_1d(
    values: jax.Array,     # [E] per-edge scalars (0 / -inf-safe on padding)
    receivers: jax.Array,  # [E] int32, sorted ascending
    plan: tuple,           # CsrPlan device arrays (block, chunk, first)
    num_segments: int,
    op: str = "sum",
) -> jax.Array:
    """Per-segment scalar ``sum`` or ``max`` via the block-CSR plan.

    The matmul trick doesn't apply to scalars (and padding a [E] column to
    128 lanes would 128x the HBM traffic), so the kernel keeps a [bn, 128]
    lane-partial accumulator per node block and the final 128-lane combine
    runs as one XLA row-reduction.  Replaces XLA's serialized scalar
    scatter (~0.8 s at 2.4 M edges) in segment-softmax attention.
    """
    assert op in ("sum", "max"), op
    m = S.mode()
    if m == "xla":
        if op == "sum":
            # match the Pallas path: accumulate in ≥f32 (summing bf16
            # terms directly drops contributions past ~256×), then cast
            # back to the input dtype like the kernel's epilogue does
            acc = jax.ops.segment_sum(
                values.astype(jnp.promote_types(values.dtype, jnp.float32)),
                receivers, num_segments, indices_are_sorted=True)
            return acc.astype(values.dtype)
        return jax.ops.segment_max(values, receivers, num_segments,
                                   indices_are_sorted=True)
    e = values.shape[0]
    bn, bk = _BN, _BK
    e_pad = S.round_up(e, bk)
    fill = 0.0 if op == "sum" else NEG_FILL
    v = jnp.pad(values.astype(jnp.float32), (0, e_pad - e),
                constant_values=fill)
    v2d = v.reshape(e_pad // bk, bk // 128, 128)
    recv2d = S.pad_axis(receivers, 0, bk).reshape(e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    n_pad = S.round_up(num_segments, bn)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first: (chk[t], 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, 128),
                               lambda t, blk, chk, first: (blk[t], 0)),
    )
    out = pl.pallas_call(
        _body_1d(bn, op),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, 128), jnp.float32),
        interpret=S.interpret_flag(m),
    )(*tuple(plan), recv2d, v2d)
    red = jnp.sum(out, axis=-1) if op == "sum" else jnp.max(out, axis=-1)
    return red[:num_segments].astype(values.dtype)


# --- fused attention backward over edges ---------------------------------------


def _body_att_bwd(bn: int, bound: float, negative_slope: float):
    def body(blk_ref, chk_ref, first_ref, firstc_ref, recv_ref, dn_ref,
             h1_ref, w_ref, lm_ref, dpre_ref, dar_ref):
        t = pl.program_id(0)
        b = blk_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            dar_ref[:] = jnp.zeros_like(dar_ref)

        @pl.when(firstc_ref[t] == 1)
        def _():
            dpre_ref[:] = jnp.zeros_like(dpre_ref)

        recv = recv_ref[0]                       # [bk//128, 128] int32
        w = w_ref[0].astype(jnp.float32)
        lm = lm_ref[0].astype(jnp.float32)
        dn = dn_ref[:].astype(jnp.float32)       # [bn, dp1] (d_num | d_den)
        local = recv - b * bn
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        dar_acc = dar_ref[:]
        for j in range(recv.shape[0]):
            oh = (rows == local[j : j + 1, :]).astype(jnp.float32)
            # per-edge pick of this block's (d_num | d_den) rows: ohT @ dn
            dn_pick = jax.lax.dot_general(      # [128, dp1], MXU
                oh, dn, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            h1 = h1_ref[j * 128 : (j + 1) * 128, :].astype(jnp.float32)
            # dw = <d_num[r], h[s]> + d_den[r]: h1 carries a ones column
            # in the d_den lane, so one row-dot covers both terms
            dw = jnp.sum(dn_pick * h1, axis=-1)            # [128]
            leaky_g = jnp.where(lm[j] >= 0.0, 1.0, negative_slope)
            dpre_j = dw * w[j] * (1.0 - (lm[j] / bound) ** 2) * leaky_g
            # foreign lanes (another block's edges in a boundary chunk)
            # have all-zero one-hots → dw = 0 → dpre_j = 0: the owning
            # block's visit supplies the value, accumulation is exact
            dpre_ref[0, j, :] += dpre_j
            dar_acc = dar_acc + jnp.where(rows == local[j : j + 1, :],
                                          jnp.broadcast_to(
                                              dpre_j[None, :], (bn, 128)),
                                          0.0)
        dar_ref[:] = dar_acc

    return body


def csr_att_bwd_edges(
    dn_ext: jax.Array,     # [N, F+1] (d_num | d_den) node rows, f32
    h1: jax.Array,         # [E, F+1] residual sender rows | ones column
    w: jax.Array,          # [E] forward softmax weights (0 on padding)
    lm: jax.Array,         # [E] bounded logits
    receivers: jax.Array,  # [E] int32 sorted
    plan: tuple,           # CsrPlan device arrays
    num_segments: int,
    bound: float,
    negative_slope: float,
) -> tuple[jax.Array, jax.Array]:
    """Fused attention-backward edge pass (nn/scatter.att_aggregate_planned).

    One walk of the CSR plan computes, per edge,
    ``dw = <d_num[r], h[s]> + d_den[r]`` (the receiver-side rows are
    picked from the VMEM-resident node block by one-hot matmul — no [E]
    gather of d_num), chains it through the bounded-logit softmax weight
    ``w = exp(B·tanh(leaky(pre)/B))`` to ``dpre``, writes the edge-
    aligned ``dpre`` stream, AND accumulates the receiver-side score
    gradient ``d_alpha_r = segsum(dpre)`` in the same pass — replacing a
    sorted [E, F] gather, an [E, F] elementwise row-dot pass, an [E]
    elementwise chain, and a scalar CSR reduction (4 HBM passes → 1).
    Twin/oracle: the unfused chain (tests/nn/test_scatter.py).
    """
    m = S.mode()
    f1 = dn_ext.shape[-1]
    if m == "xla":
        dn_r = dn_ext[receivers]
        dw = jnp.sum(dn_r * h1.astype(jnp.float32), axis=-1)
        leaky_g = jnp.where(lm >= 0.0, 1.0, negative_slope)
        dpre = (dw * w.astype(jnp.float32)
                * (1.0 - (lm / bound) ** 2) * leaky_g)
        dar = jax.ops.segment_sum(dpre, receivers, num_segments,
                                  indices_are_sorted=True)
        return dpre, dar
    e = w.shape[0]
    bn, bk = _BN, _BK
    e_pad = S.round_up(e, bk)
    dp1 = S.round_up(f1, 128)
    dn_p = S.pad_axis(S.pad_axis(dn_ext.astype(jnp.float32), -1, 128), 0, bn)
    h1_p = S.pad_axis(S.pad_axis(h1, -1, 128), 0, bk)
    w2d = jnp.pad(w.astype(jnp.float32), (0, e_pad - e)).reshape(
        e_pad // bk, bk // 128, 128)
    lm2d = jnp.pad(lm.astype(jnp.float32), (0, e_pad - e)).reshape(
        e_pad // bk, bk // 128, 128)
    recv2d = S.pad_axis(receivers, 0, bk).reshape(e_pad // bk, bk // 128, 128)
    pb, pc, pf = tuple(plan)
    # chunk indices are non-decreasing in item order (block-major plan),
    # so each chunk's first visitor is where the value changes
    fc = jnp.concatenate([jnp.ones((1,), jnp.int32),
                          (pc[1:] > pc[:-1]).astype(jnp.int32)])
    t = pb.shape[0]
    n_pad = S.round_up(num_segments, bn)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first, fc: (chk[t], 0, 0)),
            pl.BlockSpec((bn, dp1),
                         lambda t, blk, chk, first, fc: (blk[t], 0)),
            pl.BlockSpec((bk, dp1),
                         lambda t, blk, chk, first, fc: (chk[t], 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first, fc: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first, fc: (chk[t], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, blk, chk, first, fc: (chk[t], 0, 0)),
            pl.BlockSpec((bn, 128),
                         lambda t, blk, chk, first, fc: (blk[t], 0)),
        ],
    )
    dpre2d, dar = pl.pallas_call(
        _body_att_bwd(bn, bound, negative_slope),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((e_pad // bk, bk // 128, 128), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 128), jnp.float32),
        ],
        interpret=S.interpret_flag(m),
    )(pb, pc, pf, fc, recv2d, dn_p, h1_p, w2d, lm2d)
    return (dpre2d.reshape(e_pad)[:e],
            jnp.sum(dar, axis=-1)[:num_segments])
