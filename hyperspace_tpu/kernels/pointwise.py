"""Rowwise Pallas kernels: Möbius ops, exp/log maps, parallel transport.

TPU equivalents of the reference's elementwise CUDA kernels N1-N4
(SURVEY.md §2): ``mobius_add``, ``mobius_scalar_mul``, ``expmap``/``logmap``
(and their origin forms), ``ptransp`` — each fuses the whole chain of
norms, clamps, and transcendentals for a row block into one VMEM-resident
kernel pass instead of a string of HBM round-trips.

Every op dispatches per :func:`hyperspace_tpu.kernels._support.mode`:
the Pallas kernel on TPU, the :class:`PoincareBall` method (the oracle
twin) on other backends.  Gradients always flow through the twin via
``jax.custom_vjp`` — backward re-derives the op with XLA autodiff, which
both avoids hand-written transposes and acts as rematerialization
(TPU-idiomatic: trade FLOPs for HBM).

All ops accept [..., d] with broadcasting between operands; compute is
f32 inside the kernel regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds.poincare import PoincareBall


def _launch_rowwise(body, tensors, scalars, mode_):
    """Run ``body(*scalar_refs, *tensor_refs, o_ref)`` over row blocks.

    tensors: list of [N, d] arrays (identical shapes); scalars: list of
    python/traced scalars, passed as (1, 1) SMEM blocks. Output matches
    tensors[0] in shape/dtype.
    """
    n, d = tensors[0].shape
    dtype = tensors[0].dtype
    bn = S.row_block(n, dp=S.round_up(d, 128), n_bufs=len(tensors) + 1)
    padded = [S.pad_rows_lanes(t, rows_to=bn) for t in tensors]
    np_, dp = padded[0].shape
    grid = (np_ // bn,)

    smem_spec = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    vmem_spec = pl.BlockSpec((bn, dp), lambda i: (i, 0), memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        body,
        grid=grid,
        in_specs=[smem_spec] * len(scalars) + [vmem_spec] * len(tensors),
        out_specs=vmem_spec,
        out_shape=jax.ShapeDtypeStruct((np_, dp), dtype),
        interpret=S.interpret_flag(mode_),
    )(*[S.c_smem(s) for s in scalars], *padded)
    return out[:n, :d]


def _rowwise_op(twin, kernel_fn, n_tensors):
    """Build a custom-vjp op: pallas forward (twin elsewhere), twin backward.

    Signature of the produced op: (t1, ..., tn, c) with [..., d] tensors
    broadcast against each other and a scalar curvature c.
    """

    def fwd_impl(*args):
        *tensors, c = args
        m = S.mode()
        if m == "xla":
            return twin(*tensors, c)
        tensors = jnp.broadcast_arrays(*tensors) if n_tensors > 1 else list(tensors)
        flat0, lead = S.flatten_batch(tensors[0])
        flats = [flat0] + [S.flatten_batch(t)[0] for t in tensors[1:]]
        out = _launch_rowwise(kernel_fn, flats, [c], m)
        return out.reshape(lead + out.shape[-1:])

    @jax.custom_vjp
    def op(*args):
        return fwd_impl(*args)

    def op_fwd(*args):
        return fwd_impl(*args), args

    def op_bwd(res, g):
        _, vjp = jax.vjp(twin, *res)
        return vjp(g)

    op.defvjp(op_fwd, op_bwd)
    return functools.wraps(twin)(op)


# --- kernel bodies (f32 compute; zero-padded lanes are exact no-ops) ----------


def _mobius_add_body(c_ref, x_ref, y_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    o_ref[:] = S.kmobius_add(x, y, c).astype(o_ref.dtype)


def _mobius_scalar_mul_body(c_ref, r_ref, x_ref, o_ref):
    c = c_ref[0, 0]
    r = r_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    norm = jnp.maximum(S.ksafe_norm(x), S.MIN_NORM_F32)
    t = S.ktanh(r * S.kartanh(sc * norm))
    o_ref[:] = (t * x / jnp.maximum(sc * norm, S.MIN_NORM_F32)).astype(o_ref.dtype)


def _expmap_body(c_ref, x_ref, v_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    lam = S.klambda_x(x, c)
    t = sc * lam * S.ksafe_norm(v) / 2.0
    second = S.ktanc(t) * lam / 2.0 * v
    o_ref[:] = S.kproj(S.kmobius_add(x, second, c), c).astype(o_ref.dtype)


def _logmap_body(c_ref, x_ref, y_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    sub = S.kmobius_add(-x, y, c)
    lam = S.klambda_x(x, c)
    o_ref[:] = ((2.0 / lam) * S.kartanc(sc * S.ksafe_norm(sub)) * sub).astype(o_ref.dtype)


def _expmap0_body(c_ref, v_ref, o_ref):
    c = c_ref[0, 0]
    v = v_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    o_ref[:] = S.kproj(S.ktanc(sc * S.ksafe_norm(v)) * v, c).astype(o_ref.dtype)


def _logmap0_body(c_ref, y_ref, o_ref):
    c = c_ref[0, 0]
    y = y_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    o_ref[:] = (S.kartanc(sc * S.ksafe_norm(y)) * y).astype(o_ref.dtype)


def _ptransp_body(c_ref, x_ref, y_ref, v_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    lam_x = S.klambda_x(x, c)
    lam_y = S.klambda_x(y, c)
    o_ref[:] = (S.kgyration(y, -x, v, c) * lam_x / lam_y).astype(o_ref.dtype)


# --- twins (the manifold methods themselves) ----------------------------------


def _t_mobius_add(x, y, c):
    """x ⊕_c y on the Poincaré ball (reference CUDA kernel N1)."""
    return PoincareBall(c).mobius_add(x, y)


def _t_mobius_scalar_mul(x, r, c):
    """r ⊗_c x (reference CUDA kernel N2); r is the second tensor arg."""
    return PoincareBall(c).mobius_scalar_mul(r, x)


def _t_expmap(x, v, c):
    """exp_x(v) on the ball (reference CUDA kernel N3)."""
    return PoincareBall(c).expmap(x, v)


def _t_logmap(x, y, c):
    """log_x(y) on the ball (reference CUDA kernel N3)."""
    return PoincareBall(c).logmap(x, y)


def _t_expmap0(v, c):
    """exp_0(v) on the ball."""
    return PoincareBall(c).expmap0(v)


def _t_logmap0(y, c):
    """log_0(y) on the ball."""
    return PoincareBall(c).logmap0(y)


def _t_ptransp(x, y, v, c):
    """P_{x→y}(v) on the ball (reference CUDA kernel N4)."""
    return PoincareBall(c).ptransp(x, y, v)


mobius_add = _rowwise_op(_t_mobius_add, _mobius_add_body, 2)
expmap = _rowwise_op(_t_expmap, _expmap_body, 2)
logmap = _rowwise_op(_t_logmap, _logmap_body, 2)
expmap0 = _rowwise_op(_t_expmap0, _expmap0_body, 1)
logmap0 = _rowwise_op(_t_logmap0, _logmap0_body, 1)
ptransp = _rowwise_op(_t_ptransp, _ptransp_body, 3)


def _msm_fwd_impl(r, x, c):
    m = S.mode()
    if m == "xla":
        return _t_mobius_scalar_mul(x, r, c)
    flat, lead = S.flatten_batch(x)
    out = _launch_rowwise(_mobius_scalar_mul_body, [flat], [c, r], m)
    return out.reshape(lead + out.shape[-1:])


@jax.custom_vjp
def mobius_scalar_mul(r, x, c):
    """r ⊗_c x with scalar r (kernel N2); r may be traced (differentiable)."""
    return _msm_fwd_impl(r, x, c)


def _msm_fwd(r, x, c):
    return _msm_fwd_impl(r, x, c), (r, x, c)


def _msm_bwd(res, g):
    r, x, c = res
    _, vjp = jax.vjp(lambda r_, x_, c_: _t_mobius_scalar_mul(x_, r_, c_), r, x, c)
    return vjp(g)


mobius_scalar_mul.defvjp(_msm_fwd, _msm_bwd)
