"""Fused gyro-linear kernel (reference CUDA kernel N5; SURVEY.md §2).

The Poincaré gyro-linear layer  y = proj((M ⊗_c x) ⊕_c b)  (Ganea et al.
2018) is, unfused, four HBM round-trips: the matmul, the Möbius rescale of
its output, the Möbius bias addition, and the projection.  This kernel
keeps the weight resident in VMEM and performs matmul → rescale → ⊕ bias
→ proj in one pass per row block: the MXU does x @ M, the VPU does the
rest while the tile is still on-chip.

Dispatch/twin/gradient conventions are those of kernels/pointwise.py:
Pallas on TPU, the manifold-method composition as the XLA twin elsewhere
and as the custom-vjp backward (rematerializing).  Falls back to the twin
when the weight block would not fit the VMEM budget — at that size the
layer is matmul-bound and XLA's own fusion is already optimal.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds.poincare import PoincareBall


def _hyp_linear_body(c_ref, x_ref, m_ref, b_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)          # [bn, d_in_p]
    m = m_ref[:].astype(jnp.float32)          # [d_in_p, d_out_p]
    b = b_ref[0:1, :].astype(jnp.float32)     # [1, d_out_p]
    sc = jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)

    # M ⊗_c x — Möbius matvec (kernel N2 semantics on the matmul output)
    x_norm = jnp.maximum(S.ksafe_norm(x), S.MIN_NORM_F32)
    mx = jax.lax.dot_general(
        x, m, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    mx_norm = jnp.maximum(S.ksafe_norm(mx), S.MIN_NORM_F32)
    res = S.ktanh(mx_norm / x_norm * S.kartanh(sc * x_norm)) * mx / (mx_norm * sc)
    zero = jnp.max(jnp.abs(mx), axis=-1, keepdims=True) == 0.0
    res = jnp.where(zero, 0.0, res)

    out = S.kproj(S.kmobius_add(res, b, c), c)
    o_ref[:] = out.astype(o_ref.dtype)


def _t_hyp_linear(x, m, b, c):
    """XLA twin: proj((M ⊗_c x) ⊕_c b) via the manifold methods."""
    ball = PoincareBall(c)
    return ball.proj(ball.mobius_add(ball.mobius_matvec(m, x), b))


def _launch_hyp_linear(x, m, b, c, mode_):
    n, d_in = x.shape
    d_out = m.shape[1]
    di = S.round_up(d_in, 128)
    do = S.round_up(d_out, 128)
    bn = S.row_block(n, dp=max(di, do), n_bufs=3)
    xp = S.pad_rows_lanes(x, rows_to=bn)
    mp = S.pad_axis(S.pad_axis(m, 1, 128), 0, 128)  # [di, do] (zero rows/cols are exact no-ops)
    bp = S.pad_rows_lanes(b.reshape(1, -1))   # [8, d_out_p]
    np_, _ = xp.shape
    grid = (np_ // bn,)

    out = pl.pallas_call(
        _hyp_linear_body,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, di), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((di, do), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, do), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, do), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((np_, do), x.dtype),
        interpret=S.interpret_flag(mode_),
    )(S.c_smem(c), xp, mp, bp)
    return out[:n, :d_out]


def _fwd_impl(x, m, b, c):
    mode_ = S.mode()
    d_in, d_out = m.shape
    weight_bytes = 4 * S.round_up(d_in, 128) * S.round_up(d_out, 128)
    if mode_ == "xla" or weight_bytes > S.VMEM_BUDGET:
        return _t_hyp_linear(x, m, b, c)
    flat, lead = S.flatten_batch(x)
    out = _launch_hyp_linear(flat, m, b, c, mode_)
    return out.reshape(lead + out.shape[-1:])


@jax.custom_vjp
def hyp_linear(x, m, b, c):
    """Fused gyro-linear  proj((M ⊗_c x) ⊕_c b)  (kernel N5).

    x: [..., d_in] ball points; m: [d_in, d_out]; b: [d_out] ball point
    (pass zeros for a bias-free layer — x ⊕ 0 = x exactly).
    """
    return _fwd_impl(x, m, b, c)


def _hl_fwd(x, m, b, c):
    return _fwd_impl(x, m, b, c), (x, m, b, c)


def _hl_bwd(res, g):
    _, vjp = jax.vjp(_t_hyp_linear, *res)
    return vjp(g)


hyp_linear.defvjp(_hl_fwd, _hl_bwd)
