"""Fused hyperbolic-MLR kernel (reference CUDA kernel N6; SURVEY.md §2).

The naive hyperbolic softmax head (hyperspace_tpu/nn/mlr.py,
``hyp_mlr_logits``) materializes z_k = (−p_k) ⊕_c x for every
(point, class) pair — an [..., K, d] intermediate that is pure HBM
traffic.  Expanding the Möbius addition algebraically removes it: with

    α  = 1 − 2c⟨p,x⟩ + c‖x‖²          β   = 1 − c‖p‖²
    den = 1 − 2c⟨p,x⟩ + c²‖p‖²‖x‖²    (clamped like mobius_add)

the two reductions the logit needs are rank-2 expressions

    ⟨z,a⟩ = (−α⟨p,a⟩ + β⟨x,a⟩) / den
    ‖z‖²  = (α²‖p‖² − 2αβ⟨p,x⟩ + β²‖x‖²) / den² ,

so the whole [N, K] logit matrix is TWO MXU matmuls (x pᵀ and x aᵀ) plus
elementwise — the same cost shape as a Euclidean linear head.  That
expansion is both the XLA twin (used on CPU/GPU and for gradients) and
the Pallas kernel body here; ``tests/kernels/test_mlr.py`` pins both to
the naive Möbius-form oracle.

    logit_k(x) = (λ_{p_k}‖a_k‖/√c) · asinh( 2√c⟨z,a⟩ / ((1−c‖z‖²)‖a_k‖) )

(Ganea et al. 2018 eq. (25)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds import smath


_dotT = S.dotT
kasinh = S.kasinh


def _mlr_body(c_ref, x_ref, p_ref, a_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)   # [bn, dp]
    p = p_ref[:].astype(jnp.float32)   # [bk, dp]
    a = a_ref[:].astype(jnp.float32)   # [bk, dp]
    sc = jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)

    x2 = S.ksq_norm(x)                 # [bn, 1] — broadcasts over lanes
    p2 = S.ksq_norm(p)                 # [bk, 1]
    pa = jnp.sum(p * a, axis=-1, keepdims=True)                   # [bk, 1]
    a_norm = jnp.maximum(S.ksafe_sqrt(S.ksq_norm(a)), S.MIN_NORM_F32)

    ones = jnp.ones_like(x2)
    # rank-1 row broadcasts of per-class scalars (no transposes in Mosaic)
    p2_t = _dotT(ones, p2)             # [bn, bk]
    pa_t = _dotT(ones, pa)
    an_t = _dotT(ones, a_norm)

    xp = _dotT(x, p)                   # ⟨x, p_k⟩ — MXU matmul 1
    xa = _dotT(x, a)                   # ⟨x, a_k⟩ — MXU matmul 2

    alpha = 1.0 - 2.0 * c * xp + c * x2
    beta = 1.0 - c * p2_t
    den = jnp.maximum(1.0 - 2.0 * c * xp + (c * c) * p2_t * x2, S.EPS_F32)

    za = (-alpha * pa_t + beta * xa) / den
    z2 = (alpha * alpha * p2_t - 2.0 * alpha * beta * xp + beta * beta * x2) / (den * den)

    lam_p = 2.0 / jnp.maximum(1.0 - c * p2_t, S.EPS_F32)
    arg = 2.0 * sc * za / (jnp.maximum(1.0 - c * z2, S.EPS_F32) * an_t)
    o_ref[:] = ((lam_p * an_t / sc) * kasinh(arg)).astype(o_ref.dtype)


def _t_hyp_mlr(x, p, a, c):
    """XLA twin: the same expansion, vectorized (== naive hyp_mlr_logits).

    x: [..., d] ball points; p: [K, d] hyperplane base points; a: [K, d]
    tangent normals.  Returns [..., K].
    """
    cc = jnp.asarray(c, x.dtype)
    sc = smath.clamp_min(smath.sqrt_c(cc), smath.min_norm(x.dtype))
    eps = smath.eps_for(x.dtype)

    x2 = smath.sq_norm(x)                                   # [..., 1]
    p2 = smath.sq_norm(p)[:, 0]                             # [K]
    pa = jnp.sum(p * a, axis=-1)                            # [K]
    a_norm = smath.clamp_min(smath.safe_norm(a, keepdims=False),
                             smath.min_norm(x.dtype))       # [K]

    xp = jnp.matmul(x, p.T, precision=jax.lax.Precision.HIGHEST)  # [..., K]
    xa = jnp.matmul(x, a.T, precision=jax.lax.Precision.HIGHEST)  # [..., K]

    alpha = 1.0 - 2.0 * cc * xp + cc * x2
    beta = 1.0 - cc * p2
    den = smath.clamp_min(1.0 - 2.0 * cc * xp + (cc ** 2) * p2 * x2, eps)

    za = (-alpha * pa + beta * xa) / den
    z2 = (alpha ** 2 * p2 - 2.0 * alpha * beta * xp + beta ** 2 * x2) / (den ** 2)

    lam_p = 2.0 / smath.clamp_min(1.0 - cc * p2, eps)
    arg = 2.0 * sc * za / (smath.clamp_min(1.0 - cc * z2, eps) * a_norm)
    return (lam_p * a_norm / sc) * jnp.arcsinh(arg)


def _launch_mlr(x, p, a, c, mode_):
    n, d = x.shape
    k = p.shape[0]
    bn = min(S.round_up(n, 8), 256)
    bk = min(S.round_up(k, 128), 512)
    dp_ = S.round_up(d, 128)
    # x-block + p-block + a-block + out-block under the VMEM budget
    while 4 * (bn * dp_ + 2 * bk * dp_ + bn * bk) > S.VMEM_BUDGET and (bn > 8 or bk > 128):
        if bk > 128 and bk >= bn:
            bk = max(128, (bk // 2) // 128 * 128)
        else:
            bn = max(8, (bn // 2) // 8 * 8)
    xp_ = S.pad_rows_lanes(x, rows_to=bn)
    pp = S.pad_rows_lanes(p, rows_to=bk)
    ap = S.pad_rows_lanes(a, rows_to=bk)
    np_, dp = xp_.shape
    kp = pp.shape[0]
    grid = (np_ // bn, kp // bk)

    out = pl.pallas_call(
        _mlr_body,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, dp), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, dp), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, bk), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((np_, kp), x.dtype),
        interpret=S.interpret_flag(mode_),
    )(S.c_smem(c), xp_, pp, ap)
    return out[:n, :k]


def _fwd_impl(x, p, a, c):
    m = S.mode()
    if m == "xla":
        return _t_hyp_mlr(x, p, a, c)
    flat, lead = S.flatten_batch(x)
    out = _launch_mlr(flat, p, a, c, m)
    return out.reshape(lead + out.shape[-1:])


@jax.custom_vjp
def hyp_mlr(x, p, a, c):
    """Fused hyperbolic-MLR logits (kernel N6); see module docstring."""
    return _fwd_impl(x, p, a, c)


def _mlr_fwd(x, p, a, c):
    return _fwd_impl(x, p, a, c), (x, p, a, c)


def _mlr_bwd(res, g):
    _, vjp = jax.vjp(_t_hyp_mlr, *res)
    return vjp(g)


hyp_mlr.defvjp(_mlr_fwd, _mlr_bwd)
