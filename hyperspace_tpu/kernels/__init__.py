"""Pallas TPU kernel layer — TPU-native equivalents of the reference's
CUDA kernels N1-N7 (SURVEY.md §2 native inventory).

Every op in this package:

- runs as a **Pallas (Mosaic) kernel** when the default backend is TPU;
- runs its **pure-JAX twin** (the manifold-math oracle) on CPU/GPU;
- can be forced with ``HYPERSPACE_KERNELS={auto,pallas,interpret,xla}``
  (``interpret`` = Pallas interpreter on CPU, used by the parity tests);
- differentiates through the twin via ``custom_vjp`` (rematerializing
  backward — the TPU-idiomatic FLOPs-for-HBM trade).
"""

from hyperspace_tpu.kernels._support import mode
from hyperspace_tpu.kernels.distmat import lorentz_pdist, poincare_pdist
from hyperspace_tpu.kernels.attention import flash_attention
from hyperspace_tpu.kernels.hyplinear import hyp_linear
from hyperspace_tpu.kernels.mlr import hyp_mlr
# the fused scan-top-k lives at hyperspace_tpu.kernels.scan_topk
# (module-level API: scan_topk / scan_topk_cand / supports /
# fused_tile_rows) — NOT re-exported here: the entry point shares the
# module's name, and a function attribute would shadow the submodule
from hyperspace_tpu.kernels import scan_topk  # noqa: F401 — submodule export
from hyperspace_tpu.kernels.pointwise import (
    expmap,
    expmap0,
    logmap,
    logmap0,
    mobius_add,
    mobius_scalar_mul,
    ptransp,
)

__all__ = [
    "mode",
    "mobius_add",
    "mobius_scalar_mul",
    "expmap",
    "logmap",
    "expmap0",
    "logmap0",
    "ptransp",
    "poincare_pdist",
    "lorentz_pdist",
    "hyp_mlr",
    "hyp_linear",
    "flash_attention",
    "scan_topk",
]
