"""Fused streaming scan-top-k: hyperbolic k-NN without a distance matrix.

The serve hot path is HBM-bandwidth-bound, not FLOPs-bound: the
two-stage engine scan (serve/engine.py) materializes a [B, chunk]
distance tile per step, runs ``lax.top_k`` on it, and merges the stacked
candidates after the scan — every distance is written to and re-read
from memory at least once.  This kernel applies flash-attention's trick
(kernels/attention.py: the online-softmax recurrence keeps running state
in VMEM) to distance-scan-top-k:

- **Grid** ``(query blocks, table tiles)``, table tiles innermost and
  sequential.  Each step streams one ``[bm, dp]`` table tile through
  VMEM, computes the ``[bq, bm]`` distance tile **in-register** via the
  einsum-Gram closed forms (the same math as ``kernels/distmat.py``:
  one MXU matmul + cheap elementwise work; poincare / lorentz /
  euclidean), and folds it into the carry.
- **Carry** = the running per-row top-k: ``cd [bq, K]`` f32 distances
  (ascending, +inf beyond the live entries) and ``ci [bq, K]`` int32
  *global* column ids (−1 on empty slots), ``K = round_up(k, 128)``
  lanes, held in VMEM scratch for the whole tile walk.  The ``[B, N]``
  distance matrix is never written to HBM and the per-chunk
  ``lax.top_k`` + post-scan merge of the two-stage path disappear; HBM
  traffic is one table read plus ``2·B·K`` result bytes.
- **Merge** = ``k`` min-extract passes over the concatenated
  ``[bq, K + bm]`` candidate row (select row-min, pick its lowest
  column on ties, retire it to +inf) — pure VPU work, exact (extracted
  values are copies, never re-derived arithmetic), and deterministic:
  ties resolve to the lowest combined column, which is global-column
  order (carry entries come from earlier tiles).  A slot whose
  extracted distance is +inf gets id −1 (narrow shards / k > reachable
  candidates surface ``(+inf, −1)``, never a wrong row).
- **Threshold prune** (the two-stage fast path, kept): a tile whose
  per-row minimum meets the carried k-th distance on EVERY row cannot
  change the result — the merge is skipped outright.
- **Masking by index**: global column ids start at ``col0`` (shard-local
  offsets — ``_topk_sharded`` composes); rows at global index >= ``n``
  (engine zero-padding) or local index >= the slab's true rows (kernel
  tile padding) are +inf, as is each query's own row under
  ``exclude_self``.
- **bf16 tables** stream at half the HBM bytes; tiles are cast to f32
  in-register, so the scan's *arithmetic* is f32 either way (the
  low-precision cost is the table quantization only — the engine's
  f32 rescore repairs k-th-boundary near-ties, docs/precision.md).
- **int8 tables** (``scale=``; serve/quant.py) stream at a QUARTER of
  the f32 bytes: the slab is the per-row symmetric int8 code and the
  companion per-row f32 scale rides beside it as one extra streamed
  block per tile ([bm, 1] lanes against the [bm, dp] rows).  Tiles
  dequantize in-register (``rows.astype(f32) * scale``) before the
  identical distance math — same f32 arithmetic, same carry, same twin
  contract; only the table bytes shrink.  ``scale=None`` (default) is
  byte-for-byte the pre-int8 program.
- **int4 tables** (``packed=True`` + ``scale=``) stream at an EIGHTH:
  the slab is the planar two-nibble packing of ``serve/quant.py``
  (byte column j = element j low nibble, element hw+j high nibble,
  hw = ceil(D/2)), and the in-register unpack is two shifts, a
  sign-extend and a lane concatenate — element 0 stays in lane 0, so
  the Lorentz time flip and every Gram closed form run unchanged on
  the ``[bm, 2*hwp]`` unpacked tile.  Queries are re-laid to the same
  split-lane layout by :func:`int4_query_layout` (zero lanes between
  the halves are exact no-ops — sums of products).
- **PQ tables** (:func:`scan_topk_pq`) replace the Gram matmul with
  ADC: the slab is one uint8 centroid code per subspace ([M, m]), the
  per-query input is a lookup table of subspace partial sums
  (:func:`pq_lut`), and the tile math is a one-hot matmul
  ``dotT(lut, onehot(codes))`` whose row sums ARE the Lorentz inner
  product (hyperbolic lanes) or the squared distance (euclidean) of
  the RECONSTRUCTED rows — one arcosh/sqrt at the end, same carry,
  same twin contract.
- **Explicit double-buffered DMA pipeline** (ISSUE 16): the slab-side
  variants keep the grid over query blocks only and walk the table
  tiles in-kernel — two VMEM tile slots, the async HBM→VMEM copy of
  tile i+1 issued BEFORE tile i's Gram/fold math, one DMA semaphore
  per slot (the slab and its scale/code companions live in
  ``pltpu.ANY`` memory space).  The tile ORDER and math are exactly
  the implicit-grid schedule's, so the twin (and results) are
  unchanged; only the copy/compute overlap is now explicit.  The
  candidate variant keeps the implicit grid pipeline (its stream is a
  pre-gathered per-query block, already double-buffered by Pallas).

**Twin contract** (the ``kernels/distmat.py`` convention, tightened):
the XLA twin is not merely value-close — it executes the *same padded
block schedule and op sequence* (`_slab_tile` / `_cand_tile` / `_fold`
are shared functions over identically shaped blocks), so on CPU the
twin matches the Pallas kernel under the interpreter **bitwise**
(tested).  Gradients are not defined: top-k ids are integer outputs;
callers (negative mining) wrap inputs in ``stop_gradient``.

**Capability fallback**: product manifolds, ``k > FUSED_MAX_K`` or
``dim > FUSED_MAX_DIM`` are not supported — callers gate on
:func:`supports` / :func:`supports_cand` and keep the two-stage path,
bit-identical to today's default (serve/engine.py ``scan_mode="fused"``
does exactly that).

Two entry points (docs/kernels.md):

- :func:`scan_topk` — shared-slab scan: the engine's exact k-NN walk,
  the IVF builder's nearest-centroid assignment at ``k=1``
  (serve/index.py), sampled hard-negative mining
  (models/poincare_embed.py ``neg_mode="mined"``);
- :func:`scan_topk_cand` — per-query candidate rows (the IVF probing
  scorer: each query scores its OWN gathered cells' rows; grid
  ``(query blocks of 8, candidate tiles)`` with ``[8, bm, dp]`` row
  blocks and the identical carry/merge machinery).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S

# carry lanes cap: k beyond this falls back to the two-stage scan (the
# merge cost is k passes over K+bm lanes — linear in k)
FUSED_MAX_K = 256
# feature-lane cap: a [bq, dp] query block past this blows the VMEM
# schedule below
FUSED_MAX_DIM = 1024
# per-query candidate variant: cap on the pre-gathered [B, C, dp] f32
# bytes (the gather IS the input stream; a runaway probe capacity must
# fall back rather than allocate).  Judged at a NOMINAL batch — the
# fused-vs-fallback decision must be a function of the ENGINE
# configuration only, never of a request's bucket: the batcher cache
# key carries the engine's scan signature, so the same query must
# always answer through the same path whatever batch it rode in on
CAND_GATHER_BUDGET = 256 * 1024 * 1024
NOMINAL_CAND_BATCH = 1024  # the batcher's default max bucket
# PQ subspace cap: the per-query LUT block is [bq, m*256] f32 — past
# this m it stops fitting the VMEM schedule
FUSED_MAX_PQ_M = 8

_KINDS = ("poincare", "lorentz", "euclidean")
_SLAB_BQ = 256   # query rows per block (slab variant)
_CAND_BQ = 8     # query rows per block (candidate variant: [8, bm, dp])


def kind_supported(spec: tuple) -> bool:
    """Manifold families with an in-kernel closed distance form."""
    return spec[0] in _KINDS


def supports(spec: tuple, *, k: int, dim: int) -> bool:
    """Can :func:`scan_topk` serve this (spec, k, dim)?  Callers gate on
    this and fall back to the two-stage scan (bit-identical) when False."""
    return (kind_supported(spec) and 1 <= int(k) <= FUSED_MAX_K
            and int(dim) <= FUSED_MAX_DIM)


def supports_pq(spec: tuple, *, k: int, m: int) -> bool:
    """Can :func:`scan_topk_pq` serve this (spec, k, m)?  Callers gate
    on this and fall back to the two-stage decode-and-scan (the engine's
    PQ path) when False — product specs always fall back (their distance
    is not additive across a uniform subspace grid)."""
    return (kind_supported(spec) and 1 <= int(k) <= FUSED_MAX_K
            and 1 <= int(m) <= FUSED_MAX_PQ_M)


def supports_cand(spec: tuple, *, k: int, dim: int, cand: int) -> bool:
    """Can :func:`scan_topk_cand` serve this shape?  Adds the gathered
    candidate-row footprint cap to the :func:`supports` rules — judged
    at ``NOMINAL_CAND_BATCH`` rows, NOT the actual batch, so the
    decision is a function of (spec, k, dim, capacity) alone and a
    given engine serves every bucket through the same path (the cache
    signature's ``"fused"`` marker depends on it)."""
    if not supports(spec, k=k, dim=dim):
        return False
    dp = S.round_up(int(dim), 128)
    return (NOMINAL_CAND_BATCH * S.round_up(int(cand), 128) * dp * 4
            <= CAND_GATHER_BUDGET)


def fused_tile_rows(dim: int, dtype, k: int, *,
                    tile_budget: int = S.VMEM_BUDGET,
                    bq: int = _SLAB_BQ, allow_tuned: bool = True,
                    lane: str = "dense", pq_m: int = 0) -> int:
    """Table-tile rows for the slab kernel.

    A **tuned entry** for this (dim, dtype, k) on the current device
    kind wins when one exists (``kernels/autotune.py`` — the empirical
    table ``scripts/autotune_scan_topk.py`` persists; consulted only at
    the default budget/bq, since a caller passing its own budget is
    asking the model a question the table never measured).  Otherwise
    the static dim × dtype × k VMEM-footprint model below (NOT a
    fixed-byte distance-tile budget: the fused working set is the
    double-buffered table tile + the query block + the carry + the
    merge temporaries) — deterministic and pinned by tests.  Tile
    choice is result-invisible either way (the merge extracts exact
    copies with global-column tie-breaks — tested bitwise across
    tiles), so a missing/stale table costs only speed.  A tuned entry
    is CLAMPED to the static model's answer: the model is the VMEM-fit
    bound a real chip's Mosaic enforces, so a stale table (tuned under
    a looser footprint) can never hand the kernel a tile that only the
    CPU twin would accept.  The engine's ``auto_chunk_rows`` delegates
    here for ``scan_mode="fused"``.

    ``lane`` extends the model to the packed lanes (ISSUE 16) without
    touching the dense answers: ``"int4"`` counts the half-width packed
    byte tile PLUS its full-width f32 unpack temporary and the scale
    block; ``"pq"`` (with ``pq_m`` subspaces) counts the [bm, 128] code
    tile, the per-query [bq, m*256] LUT block and the one-hot matmul
    temporaries.  Packed lanes never consult the tuned table (its keys
    are element dtypes; the static model is the only authority)."""
    tuned = None
    if (lane == "dense" and allow_tuned and tile_budget == S.VMEM_BUDGET
            and bq == _SLAB_BQ):
        from hyperspace_tpu.kernels import autotune

        tuned = autotune.lookup("slab", dim, dtype, k)
    dp = S.round_up(int(dim), 128)
    kp = S.round_up(int(k), 128)
    dt = jnp.dtype(dtype)
    it = dt.itemsize
    # int8 slabs stream a companion [bm, 128] f32 per-row-scale block
    # per tile (double-buffered like the slab) — at dim <= 128 that is
    # 4× the int8 tile bytes, so the fit model MUST count it: this
    # model is the VMEM bound the engine's fused demotion check and
    # the autotune clamp both trust
    scale_bytes = (2 * 128 * 4) if dt.kind == "i" else 0

    def footprint(bm: int) -> int:
        if lane == "int4":
            wp = S.round_up((int(dim) + 1) // 2, 128)  # packed byte lanes
            return (2 * bm * wp               # double-buffered packed tile
                    + 2 * bm * 128 * 4        # streamed f32 scale block
                    + bm * 2 * wp * 4         # unpacked f32 tile temporary
                    + bq * 2 * wp * 4         # query block (split-lane)
                    + bq * 128 * 4
                    + 2 * bq * kp * 4
                    + 3 * bq * (kp + bm) * 4)
        if lane == "pq":
            mlut = max(int(pq_m), 1) * 256
            return (2 * bm * 128              # double-buffered code tile
                    + bq * mlut * 4           # per-query LUT block
                    + 2 * bm * mlut * 4       # one-hot + compare temporaries
                    + bq * 128 * 4
                    + 2 * bq * kp * 4
                    + 3 * bq * (kp + bm) * 4)
        return (2 * bm * dp * it          # double-buffered table tile
                + bm * scale_bytes        # int8: streamed scale block
                + bq * dp * 4             # query block (f32 compute copy)
                + bq * 128 * 4            # q_idx block
                + 2 * bq * kp * 4         # carry scratch (dists + ids)
                + 3 * bq * (kp + bm) * 4)  # merge concat temporaries

    bm = 1024
    while bm > 128 and footprint(bm) > tile_budget:
        bm //= 2
    return bm if tuned is None else min(tuned, bm)


def fused_cand_tile_rows(dim: int, dtype, k: int, *,
                         tile_budget: int = S.VMEM_BUDGET,
                         bq: int = _CAND_BQ,
                         allow_tuned: bool = True) -> int:
    """Candidate-tile rows for the per-query variant: the row block is
    3-D ``[bq, bm, dp]`` so the footprint scales with bq × bm × dp.
    Tuned-table consultation, static-model clamp and fallback exactly
    as :func:`fused_tile_rows` (variant ``"cand"``)."""
    tuned = None
    if allow_tuned and tile_budget == S.VMEM_BUDGET and bq == _CAND_BQ:
        from hyperspace_tpu.kernels import autotune

        tuned = autotune.lookup("cand", dim, dtype, k)
    dp = S.round_up(int(dim), 128)
    kp = S.round_up(int(k), 128)
    dt = jnp.dtype(dtype)
    it = dt.itemsize
    # int8 candidates gather a [bq, bm] f32 scale block per tile
    # (double-buffered) — counted for the same reason as the slab model
    scale_bytes = (2 * 4) if dt.kind == "i" else 0

    def footprint(bm: int) -> int:
        return (2 * bq * bm * dp * it     # double-buffered row block
                + bq * bm * scale_bytes   # int8: gathered scale block
                + bq * bm * dp * 4        # f32 compute copy
                + bq * dp * 4 + bq * 128 * 4
                + 2 * bq * kp * 4         # carry scratch
                + 3 * bq * (kp + bm) * 4  # merge temporaries
                + 2 * bq * bm * 4)        # distance + id tiles

    bm = 1024
    while bm > 128 and footprint(bm) > tile_budget:
        bm //= 2
    return bm if tuned is None else min(tuned, bm)


# --- shared tile math (kernel body AND twin run exactly this) -----------------


def _pair_dist(kind: str, c, q: jax.Array, rows: jax.Array) -> jax.Array:
    """[r, dp] × [m, dp] → [r, m] distances, f32, closed forms (same
    clamping policy as the kernels/distmat.py bodies; zero-padded
    feature lanes are exact no-ops — sums of products)."""
    if kind == "lorentz":
        lane = jax.lax.broadcasted_iota(jnp.int32, rows.shape, dimension=1)
        y_flip = jnp.where(lane == 0, -rows, rows)
        gram = S.dotT(q, y_flip)                         # ⟨q, y⟩_L
        u = jnp.maximum(-c * gram - 1.0, 0.0)
        return S.karcosh1p(u) / jnp.maximum(S.ksafe_sqrt(c),
                                            S.MIN_NORM_F32)
    gram = S.dotT(q, rows)
    xx = S.ksq_norm(q)                                   # [r, 1]
    yy = S.ksq_norm(rows)                                # [m, 1]
    ones = jnp.ones_like(xx)
    yy_t = S.dotT(ones, yy)                              # [r, m] rank-1
    d2 = jnp.maximum(xx - 2.0 * gram + yy_t, 0.0)
    if kind == "euclidean":
        return S.ksafe_sqrt(d2)
    den = S.dotT(1.0 - c * xx, 1.0 - c * yy)
    u = 2.0 * c * d2 / jnp.maximum(den, S.EPS_F32)
    return S.karcosh1p(u) / jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)


def _pair_dist_b(kind: str, c, q: jax.Array, rows: jax.Array) -> jax.Array:
    """Batched per-query form: [r, dp] × [r, m, dp] → [r, m] (the IVF
    candidate variant — rows differ per query, so the Gram is an
    elementwise-multiply-and-lane-reduce, not a shared matmul)."""
    if kind == "lorentz":
        lane = jax.lax.broadcasted_iota(jnp.int32, rows.shape, dimension=2)
        y_flip = jnp.where(lane == 0, -rows, rows)
        gram = jnp.sum(q[:, None, :] * y_flip, axis=-1)  # [r, m]
        u = jnp.maximum(-c * gram - 1.0, 0.0)
        return S.karcosh1p(u) / jnp.maximum(S.ksafe_sqrt(c),
                                            S.MIN_NORM_F32)
    gram = jnp.sum(q[:, None, :] * rows, axis=-1)        # [r, m]
    xx = jnp.sum(q * q, axis=-1, keepdims=True)          # [r, 1]
    yy = jnp.sum(rows * rows, axis=-1)                   # [r, m]
    d2 = jnp.maximum(xx - 2.0 * gram + yy, 0.0)
    if kind == "euclidean":
        return S.ksafe_sqrt(d2)
    den = jnp.maximum((1.0 - c * xx) * (1.0 - c * yy), S.EPS_F32)
    u = 2.0 * c * d2 / den
    return S.karcosh1p(u) / jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)


def _unpack_int4_tile(raw: jax.Array) -> jax.Array:
    """Shared in-register int4 unpack (kernel body AND twin): a packed
    [r, wp] uint8 tile → f32 [r, 2*wp] codes in the planar split-lane
    layout (low nibbles first, sign-extended two's complement).  Zero
    pad bytes unpack to zero codes — exact no-ops downstream."""
    t = raw.astype(jnp.int32)
    lo = t & 15
    hi = t >> 4
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)


def int4_query_layout(q: jax.Array, dim: int) -> jax.Array:
    """Re-lay f32 queries [B, dim] to the unpacked int4 tile's
    split-lane layout [B, 2*wp] (``wp = round_up(ceil(dim/2), 128)``):
    elements 0..hw-1 in lanes 0.., elements hw..dim-1 starting at lane
    wp.  The zero lanes between the halves match the tile's unpacked
    pad bytes, so every Gram closed form is exact; element 0 stays in
    lane 0 (the Lorentz time flip).  Shared by the launcher and the
    twin — ONE layout recipe."""
    b = q.shape[0]
    hw = (int(dim) + 1) // 2
    wp = S.round_up(hw, 128)
    out = jnp.zeros((b, 2 * wp), jnp.float32)
    out = out.at[:, :hw].set(q[:, :hw].astype(jnp.float32))
    out = out.at[:, wp:wp + (dim - hw)].set(
        q[:, hw:dim].astype(jnp.float32))
    return out


def pq_lut(q_lift: jax.Array, codebooks: jax.Array, *,
           kind: str) -> jax.Array:
    """Per-query ADC lookup table [B, m*256] f32 from LIFTED queries
    [B, >=m*ds] and codebooks [m, 256, ds] (serve/quant.py).

    For the lorentz-gram families the scan distance depends on a
    candidate row only through ``⟨q_L, y_L⟩_L``, which is additive over
    subspaces once the GLOBAL time lane's sign is folded into the query
    — so ``LUT[b, s*256+j] = <q_s ⊙ flip_s, cb[s, j]>`` and the tile's
    row sum IS the Lorentz inner product of q with the reconstruction.
    For euclidean, ``LUT[b, s*256+j] = ‖q_s − cb[s, j]‖²`` and the row
    sum is the squared distance.  :func:`_pq_dist_from_sum` applies the
    one closing transform."""
    m, ncent, ds = codebooks.shape
    b = q_lift.shape[0]
    if q_lift.shape[1] < m * ds:
        # the codebooks' pad lanes are exactly zero (trained on
        # zero-padded lifts), so zero query pad lanes are exact no-ops
        q_lift = jnp.concatenate(
            [q_lift, jnp.zeros((b, m * ds - q_lift.shape[1]),
                               q_lift.dtype)], axis=1)
    qs = q_lift[:, :m * ds].reshape(b, m, ds).astype(jnp.float32)
    cb = codebooks.astype(jnp.float32)
    if kind == "euclidean":
        diff = qs[:, :, None, :] - cb[None]              # [B, m, 256, ds]
        lut = jnp.sum(diff * diff, axis=-1)
    else:
        # global lane 0 = the lift's time coordinate = subspace 0 lane 0
        sign = jnp.ones((m, ds), jnp.float32).at[0, 0].set(-1.0)
        lut = jnp.einsum("bmd,mjd->bmj", qs * sign[None], cb,
                         precision=jax.lax.Precision.HIGHEST)
    return lut.reshape(b, m * ncent)


def _pq_dist_from_sum(kind: str, c, ssum: jax.Array) -> jax.Array:
    """Close the ADC partial sums into distances (same clamping policy
    as :func:`_pair_dist`, applied to the RECONSTRUCTED rows)."""
    if kind == "euclidean":
        return S.ksafe_sqrt(ssum)
    u = jnp.maximum(-c * ssum - 1.0, 0.0)
    return S.karcosh1p(u) / jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)


def _pq_tile(kind: str, exclude_self: bool, c, n, nloc, col0, loc_base,
             m: int, lut: jax.Array, qi: jax.Array, codes: jax.Array):
    """One PQ slab tile → masked distances + global column ids, the
    ``_slab_tile`` contract via ADC: ``codes`` [r, 128] int32 (the
    uint8 code tile widened; lanes >= m are pad), ``lut`` [bq, m*256].
    The per-subspace one-hot matmul sums LUT entries row-wise — MXU
    work in the kernel, the identical dot in the twin (bitwise: 0/1
    weights select exact copies)."""
    parts = []
    for s in range(m):
        cent = jax.lax.broadcasted_iota(
            jnp.int32, (codes.shape[0], 256), dimension=1)
        parts.append((codes[:, s:s + 1] == cent).astype(jnp.float32))
    oh = jnp.concatenate(parts, axis=-1)                 # [r, m*256]
    ssum = S.dotT(lut, oh)                               # [bq, r]
    d = _pq_dist_from_sum(kind, c, ssum)
    lcol = jax.lax.broadcasted_iota(jnp.int32, d.shape, dimension=1)
    loc = loc_base + lcol
    gcol = (col0 + loc).astype(jnp.int32)
    mask = (loc >= nloc) | (gcol >= n)
    if exclude_self:
        mask = mask | (gcol == qi)
    return jnp.where(mask, jnp.inf, d), gcol


def _slab_tile(kind: str, exclude_self: bool, c, n, nloc, col0, loc_base,
               q: jax.Array, qi: jax.Array, rows: jax.Array):
    """One slab tile → (d [r, m] with masked slots +inf, global column
    ids [r, m] int32).  ``loc_base`` = tile offset within the slab (may
    be traced); ``n`` global valid rows; ``nloc`` the slab's true local
    rows (kernel padding beyond it must not alias the next shard's
    columns); ``qi`` [r, 1] query row ids for ``exclude_self``."""
    d = _pair_dist(kind, c, q, rows)
    lcol = jax.lax.broadcasted_iota(jnp.int32, d.shape, dimension=1)
    loc = loc_base + lcol
    gcol = (col0 + loc).astype(jnp.int32)
    mask = (loc >= nloc) | (gcol >= n)
    if exclude_self:
        mask = mask | (gcol == qi)
    return jnp.where(mask, jnp.inf, d), gcol


def _cand_tile(kind: str, exclude_self: bool, c, q: jax.Array,
               qi: jax.Array, rows: jax.Array, ids: jax.Array):
    """One candidate tile: ``ids`` [r, m] int32 (−1 = padding) carry the
    validity; masked slots are +inf."""
    d = _pair_dist_b(kind, c, q, rows)
    mask = ids < 0
    if exclude_self:
        mask = mask | (ids == qi)
    return jnp.where(mask, jnp.inf, d), ids


def _merge(cd: jax.Array, ci: jax.Array, d: jax.Array, ids: jax.Array,
           k: int):
    """Fold a masked tile into the carry: k min-extract passes over the
    concatenated [r, K+m] row (module docstring "Merge")."""
    cat_d = jnp.concatenate([cd, d], axis=1)             # [r, K+m]
    cat_i = jnp.concatenate([ci, ids], axis=1)
    cols = jax.lax.broadcasted_iota(jnp.int32, cat_d.shape, dimension=1)
    kcols = jax.lax.broadcasted_iota(jnp.int32, cd.shape, dimension=1)
    big = cat_d.shape[1]

    def body(j, carry):
        rem, ncd, nci = carry
        m = jnp.min(rem, axis=1, keepdims=True)          # [r, 1]
        a = jnp.min(jnp.where(rem == m, cols, big), axis=1, keepdims=True)
        sel = cols == a
        idv = jnp.max(jnp.where(sel, cat_i, -1), axis=1, keepdims=True)
        idv = jnp.where(jnp.isinf(m), -1, idv)
        ncd = jnp.where(kcols == j, m, ncd)
        nci = jnp.where(kcols == j, idv, nci)
        return jnp.where(sel, jnp.inf, rem), ncd, nci

    _, ncd, nci = jax.lax.fori_loop(
        0, k, body, (cat_d, jnp.full_like(cd, jnp.inf),
                     jnp.full_like(ci, -1)))
    return ncd, nci


def _prune(cd: jax.Array, d: jax.Array, k: int):
    """True when NO row of the tile can improve the carried top-k (the
    two-stage threshold-prune condition, applied to the exact carry —
    ``cd[:, k-1]`` IS the running k-th distance, not an upper bound)."""
    kth = cd[:, k - 1:k]
    return jnp.all(jnp.min(d, axis=1, keepdims=True) >= kth)


def _fold(cd, ci, d, ids, k):
    """Prune-or-merge as a pure function (the twin's step; the kernel
    body expresses the same fold with ``pl.when`` over scratch)."""
    return jax.lax.cond(
        _prune(cd, d, k),
        lambda args: (args[0], args[1]),
        lambda args: _merge(*args, k=k),
        (cd, ci, d, ids))


# --- slab variant -------------------------------------------------------------


def _slab_schedule(b: int, dim: int, k: int, tile_rows: int):
    bq = min(S.round_up(max(b, 1), 8), _SLAB_BQ)
    dp = S.round_up(dim, 128)
    kp = S.round_up(k, 128)
    bm = int(tile_rows)
    if bm <= 0 or bm % 128:
        raise ValueError(f"tile_rows must be a positive multiple of 128; "
                         f"got {tile_rows}")
    return bq, dp, kp, bm


def _slab_pad(slab, q, q_idx, bq, bm):
    """The ONE padding recipe both implementations consume: zero lanes/
    rows on the slab and query block, q_idx broadcast to a 128-lane
    int32 block (row ids < 0 on padded query rows so ``exclude_self``
    can never fire on them)."""
    yp = S.pad_rows_lanes(slab, rows_to=bm)
    qp = S.pad_rows_lanes(q, rows_to=bq)
    qip = jnp.broadcast_to(
        jnp.asarray(q_idx, jnp.int32)[:, None], (q.shape[0], 128))
    pad = qp.shape[0] - qip.shape[0]
    if pad:
        qip = jnp.concatenate(
            [qip, jnp.full((pad, 128), -1, jnp.int32)], axis=0)
    return yp, qp, qip


def _scale_pad(scale, bm):
    """Shared per-row-scale padding (int8 slabs): [M] / [M, 1] f32 →
    a [mp, 128] lane-aligned block, rows zero-padded to the tile grid
    (a zero scale dequantizes padding rows to zero — masked anyway,
    identically in kernel and twin)."""
    s = jnp.asarray(scale, jnp.float32)
    if s.ndim == 1:
        s = s[:, None]
    if s.ndim != 2 or s.shape[1] != 1:
        raise ValueError(f"scale must be [M] or [M, 1]; got {s.shape}")
    return S.pad_rows_lanes(s, rows_to=bm)


def _tile_rows_f32(lane: str, raw: jax.Array, sblk) -> jax.Array:
    """The ONE tile-dequantize recipe (kernel body AND twin consume it
    on identically shaped blocks): dense/bf16 tiles cast to f32, scaled
    lanes multiply the per-row scale in-register, int4 tiles unpack
    first (serve/quant.py's planar layout)."""
    if lane == "int4":
        return _unpack_int4_tile(raw) * sblk[:, :1]
    rows = raw.astype(jnp.float32)
    if lane == "int8":
        rows = rows * sblk[:, :1]
    return rows


def _slab_body(kind: str, k: int, bm: int, ntiles: int, exclude_self: bool,
               lane: str = "dense"):
    """The double-buffered slab kernel body (module docstring "Explicit
    double-buffered DMA pipeline"): grid over query blocks only, table
    tiles walked in-kernel — tile i+1's HBM→VMEM copy starts before
    tile i's distance/fold math, alternating two VMEM slots."""
    quant = lane in ("int8", "int4")

    def body(c_ref, col0_ref, n_ref, nloc_ref, q_ref, qi_ref, y_hbm,
             *rest):
        if quant:  # scaled slab: the per-row scale rides beside it
            s_hbm = rest[0]
            rest = rest[1:]
        od_ref, oi_ref = rest[:2]
        if quant:
            cd_scr, ci_scr, ybuf, ysem, sbuf, ssem = rest[2:]
        else:
            cd_scr, ci_scr, ybuf, ysem = rest[2:]
        cd_scr[:] = jnp.full_like(cd_scr, jnp.inf)
        ci_scr[:] = jnp.full_like(ci_scr, -1)
        c = c_ref[0, 0]
        col0 = col0_ref[0, 0]
        n = n_ref[0, 0]
        nloc = nloc_ref[0, 0]
        q = q_ref[:].astype(jnp.float32)
        qi = qi_ref[:, :1]

        def copy_y(slot, i):
            return pltpu.make_async_copy(
                y_hbm.at[pl.ds(i * bm, bm), :], ybuf.at[slot],
                ysem.at[slot])

        def copy_s(slot, i):
            return pltpu.make_async_copy(
                s_hbm.at[pl.ds(i * bm, bm), :], sbuf.at[slot],
                ssem.at[slot])

        copy_y(0, 0).start()
        if quant:
            copy_s(0, 0).start()

        def tile(jt, _):
            slot = jax.lax.rem(jt, 2)

            @pl.when(jt + 1 < ntiles)
            def _prefetch():
                nxt = jax.lax.rem(jt + 1, 2)
                copy_y(nxt, jt + 1).start()
                if quant:
                    copy_s(nxt, jt + 1).start()

            copy_y(slot, jt).wait()
            sblk = None
            if quant:
                copy_s(slot, jt).wait()
                sblk = sbuf[slot]
            rows = _tile_rows_f32(lane, ybuf[slot], sblk)
            d, gids = _slab_tile(kind, exclude_self, c, n, nloc, col0,
                                 jt * bm, q, qi, rows)
            skip = _prune(cd_scr[:], d, k)

            @pl.when(jnp.logical_not(skip))
            def _merge_tile():
                ncd, nci = _merge(cd_scr[:], ci_scr[:], d, gids, k)
                cd_scr[:] = ncd
                ci_scr[:] = nci

            return 0

        jax.lax.fori_loop(0, ntiles, tile, 0)
        od_ref[:] = cd_scr[:]
        oi_ref[:] = ci_scr[:]

    return body


def _launch_slab(slab, q, q_idx, col0, *, kind, c, k, n, bm, exclude_self,
                 mode_, scale=None, lane="dense"):
    b = q.shape[0]
    bq, dp, kp, bm = _slab_schedule(b, q.shape[1], k, bm)
    nloc = slab.shape[0]
    yp, qp, qip = _slab_pad(slab, q, q_idx, bq, bm)
    bp, mp_ = qp.shape[0], yp.shape[0]
    ntiles = mp_ // bm
    wp = yp.shape[1]  # packed byte lanes (int4) or dp
    grid = (bp // bq,)
    smem = lambda: pl.BlockSpec((1, 1), lambda iq: (0, 0),
                                memory_space=pltpu.SMEM)
    i32 = lambda v: jnp.asarray(v, jnp.int32).reshape(1, 1)
    in_specs = [
        smem(), smem(), smem(), smem(),
        pl.BlockSpec((bq, dp), lambda iq: (iq, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bq, 128), lambda iq: (iq, 0),
                     memory_space=pltpu.VMEM),
        # the slab stays in HBM: the body's DMA pipeline streams it
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [S.c_smem(c), i32(col0), i32(n), i32(nloc), qp, qip, yp]
    scratch = [
        pltpu.VMEM((bq, kp), jnp.float32),
        pltpu.VMEM((bq, kp), jnp.int32),
        # two tile slots + one DMA semaphore per slot
        pltpu.VMEM((2, bm, wp), yp.dtype),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    if scale is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        operands.append(_scale_pad(scale, bm))
        scratch += [pltpu.VMEM((2, bm, 128), jnp.float32),
                    pltpu.SemaphoreType.DMA((2,))]
    od, oi = pl.pallas_call(
        _slab_body(kind, k, bm, ntiles, exclude_self, lane=lane),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bq, kp), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, kp), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, kp), jnp.float32),
            jax.ShapeDtypeStruct((bp, kp), jnp.int32),
        ],
        scratch_shapes=scratch,
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=S.interpret_flag(mode_),
    )(*operands)
    return od[:b, :k], oi[:b, :k]


def _t_scan_topk(slab, q, q_idx, col0, *, kind, c, k, n, bm, exclude_self,
                 scale=None, lane="dense"):
    """XLA twin: the SAME padded block schedule as the Pallas launcher,
    folded with the same shared tile/merge/dequantize functions —
    bitwise-identical to interpreter mode on CPU (tested).  Runs the
    per-query-block walk as a ``fori_loop`` over tiles with the carry
    as loop state (the kernel's DMA pipeline reorders COPIES only, so
    the twin needs no pipeline model)."""
    b = q.shape[0]
    bq, dp, kp, bm = _slab_schedule(b, q.shape[1], k, bm)
    nloc = jnp.int32(slab.shape[0])
    yp, qp, qip = _slab_pad(slab, q, q_idx, bq, bm)
    sp = None if scale is None else _scale_pad(scale, bm)
    ntiles = yp.shape[0] // bm
    c32 = jnp.asarray(c, jnp.float32)
    col0_ = jnp.asarray(col0, jnp.int32)
    n_ = jnp.int32(n)
    outs_d, outs_i = [], []
    for ib in range(qp.shape[0] // bq):
        qb = qp[ib * bq:(ib + 1) * bq].astype(jnp.float32)
        qib = qip[ib * bq:(ib + 1) * bq, :1]

        def tile_body(jt, carry, qb=qb, qib=qib):
            cd, ci = carry
            raw = jax.lax.dynamic_slice_in_dim(yp, jt * bm, bm)
            sblk = None if sp is None else jax.lax.dynamic_slice_in_dim(
                sp, jt * bm, bm)
            rows = _tile_rows_f32(lane, raw, sblk)
            d, gids = _slab_tile(kind, exclude_self, c32, n_, nloc, col0_,
                                 jt * bm, qb, qib, rows)
            return _fold(cd, ci, d, gids, k)

        cd, ci = jax.lax.fori_loop(
            0, ntiles, tile_body,
            (jnp.full((bq, kp), jnp.inf, jnp.float32),
             jnp.full((bq, kp), -1, jnp.int32)))
        outs_d.append(cd)
        outs_i.append(ci)
    od = jnp.concatenate(outs_d, axis=0)
    oi = jnp.concatenate(outs_i, axis=0)
    return od[:b, :k], oi[:b, :k]


def scan_topk(slab, q, q_idx, col0, *, spec: tuple, k: int, n: int,
              exclude_self: bool = False, tile_rows: int = 0, scale=None,
              packed: bool = False):
    """Streaming top-k of ``q`` [B, D] against the shared row block
    ``slab`` [M, D] → ``(dists ascending f32 [B, k], ids int32 [B, k])``.

    ``ids`` are GLOBAL column ids ``col0 + local`` (``col0`` may be
    traced — shard-local offsets compose); rows at global index >= ``n``
    are masked, as is each query's own row when ``exclude_self`` (by
    ``q_idx`` [B] int32 — pass zeros when unused).  Slots beyond the
    reachable candidates are ``(+inf, −1)``.  ``tile_rows`` (multiple of
    128; 0 = :func:`fused_tile_rows`) is the streamed tile height.

    ``scale`` (the int8 lane, serve/quant.py): per-row dequant scales
    ([M] or [M, 1]) for an int8 ``slab`` — each streamed tile is
    dequantized in-register (``rows.astype(f32) * scale``) before the
    shared distance math, so results are those of the DEQUANTIZED table
    at f32 arithmetic, at a quarter of the table bytes.

    ``packed=True`` (the int4 lane): ``slab`` is the planar two-nibble
    packing [M, ceil(D/2)] uint8 of ``serve/quant.py:pack_int4_rows``
    and ``scale`` is REQUIRED; queries stay [B, D] f32 — the split-lane
    relayout (:func:`int4_query_layout`) and the in-register unpack are
    internal and identical in kernel and twin.

    Dispatch follows ``kernels._support.mode()``: the Pallas kernel on
    TPU, the bitwise-identical XLA twin elsewhere.  Callers gate shapes
    with :func:`supports` — unsupported ones raise here."""
    dim = q.shape[1]
    if packed:
        if scale is None:
            raise ValueError("scan_topk: packed=True (int4) requires scale=")
        hw = (int(dim) + 1) // 2
        if slab.shape[1] != hw:
            raise ValueError(
                f"scan_topk: packed slab width {slab.shape[1]} != "
                f"ceil(dim/2) = {hw} for dim={dim}")
    elif slab.shape[1] != dim:
        raise ValueError(
            f"scan_topk: slab dim {slab.shape[1]} != query dim {dim}")
    if not supports(spec, k=k, dim=dim):
        raise ValueError(
            f"scan_topk: unsupported (spec={spec[0]!r}, k={k}, "
            f"dim={dim}) — gate on scan_topk.supports() and "
            "fall back to the two-stage scan")
    kind = spec[0]
    c = 0.0 if kind == "euclidean" else spec[1]
    lane = "int4" if packed else ("int8" if scale is not None else "dense")
    bm = int(tile_rows) or fused_tile_rows(
        dim, slab.dtype, k, lane=("int4" if packed else "dense"))
    if packed:
        # ONE relayout recipe feeds both implementations
        q = int4_query_layout(q, dim)
    m_ = S.mode()
    if m_ == "xla":
        return _t_scan_topk(slab, q, q_idx, col0, kind=kind, c=c, k=int(k),
                            n=int(n), bm=bm, exclude_self=bool(exclude_self),
                            scale=scale, lane=lane)
    return _launch_slab(slab, q, q_idx, col0, kind=kind, c=c, k=int(k),
                        n=int(n), bm=bm, exclude_self=bool(exclude_self),
                        mode_=m_, scale=scale, lane=lane)


# --- PQ slab variant (ADC over coded tiles) -----------------------------------


def _pq_body(kind: str, k: int, bm: int, ntiles: int, m: int,
             exclude_self: bool):
    """Double-buffered DMA pipeline over the [M, m] code slab — the
    ``_slab_body`` structure with the ADC tile math."""

    def body(c_ref, col0_ref, n_ref, nloc_ref, lut_ref, qi_ref, y_hbm,
             od_ref, oi_ref, cd_scr, ci_scr, ybuf, ysem):
        cd_scr[:] = jnp.full_like(cd_scr, jnp.inf)
        ci_scr[:] = jnp.full_like(ci_scr, -1)
        c = c_ref[0, 0]
        col0 = col0_ref[0, 0]
        n = n_ref[0, 0]
        nloc = nloc_ref[0, 0]
        lut = lut_ref[:].astype(jnp.float32)
        qi = qi_ref[:, :1]

        def copy_y(slot, i):
            return pltpu.make_async_copy(
                y_hbm.at[pl.ds(i * bm, bm), :], ybuf.at[slot],
                ysem.at[slot])

        copy_y(0, 0).start()

        def tile(jt, _):
            slot = jax.lax.rem(jt, 2)

            @pl.when(jt + 1 < ntiles)
            def _prefetch():
                copy_y(jax.lax.rem(jt + 1, 2), jt + 1).start()

            copy_y(slot, jt).wait()
            codes = ybuf[slot].astype(jnp.int32)
            d, gids = _pq_tile(kind, exclude_self, c, n, nloc, col0,
                               jt * bm, m, lut, qi, codes)
            skip = _prune(cd_scr[:], d, k)

            @pl.when(jnp.logical_not(skip))
            def _merge_tile():
                ncd, nci = _merge(cd_scr[:], ci_scr[:], d, gids, k)
                cd_scr[:] = ncd
                ci_scr[:] = nci

            return 0

        jax.lax.fori_loop(0, ntiles, tile, 0)
        od_ref[:] = cd_scr[:]
        oi_ref[:] = ci_scr[:]

    return body


def _launch_pq(codes, lut, q_idx, col0, *, kind, c, k, n, m, bm,
               exclude_self, mode_):
    b = lut.shape[0]
    bq, _, kp, bm = _slab_schedule(b, lut.shape[1], k, bm)
    nloc = codes.shape[0]
    # the shared slab padding recipe, with the LUT as the query block
    yp, lutp, qip = _slab_pad(codes, lut, q_idx, bq, bm)
    bp, mp_ = lutp.shape[0], yp.shape[0]
    ntiles = mp_ // bm
    grid = (bp // bq,)
    smem = lambda: pl.BlockSpec((1, 1), lambda iq: (0, 0),
                                memory_space=pltpu.SMEM)
    i32 = lambda v: jnp.asarray(v, jnp.int32).reshape(1, 1)
    od, oi = pl.pallas_call(
        _pq_body(kind, k, bm, ntiles, m, exclude_self),
        grid=grid,
        in_specs=[
            smem(), smem(), smem(), smem(),
            pl.BlockSpec((bq, lutp.shape[1]), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, 128), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((bq, kp), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, kp), lambda iq: (iq, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, kp), jnp.float32),
            jax.ShapeDtypeStruct((bp, kp), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, kp), jnp.float32),
            pltpu.VMEM((bq, kp), jnp.int32),
            pltpu.VMEM((2, bm, yp.shape[1]), yp.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=S.interpret_flag(mode_),
    )(S.c_smem(c), i32(col0), i32(n), i32(nloc), lutp, qip, yp)
    return od[:b, :k], oi[:b, :k]


def _t_scan_topk_pq(codes, lut, q_idx, col0, *, kind, c, k, n, m, bm,
                    exclude_self):
    """XLA twin of the PQ kernel: same padded blocks, same shared
    ``_pq_tile`` (the one-hot dot selects exact LUT copies, so the twin
    matches the interpreter bitwise like every other lane)."""
    b = lut.shape[0]
    bq, _, kp, bm = _slab_schedule(b, lut.shape[1], k, bm)
    nloc = jnp.int32(codes.shape[0])
    yp, lutp, qip = _slab_pad(codes, lut, q_idx, bq, bm)
    ntiles = yp.shape[0] // bm
    c32 = jnp.asarray(c, jnp.float32)
    col0_ = jnp.asarray(col0, jnp.int32)
    n_ = jnp.int32(n)
    outs_d, outs_i = [], []
    for ib in range(lutp.shape[0] // bq):
        lutb = lutp[ib * bq:(ib + 1) * bq].astype(jnp.float32)
        qib = qip[ib * bq:(ib + 1) * bq, :1]

        def tile_body(jt, carry, lutb=lutb, qib=qib):
            cd, ci = carry
            ctile = jax.lax.dynamic_slice_in_dim(
                yp, jt * bm, bm).astype(jnp.int32)
            d, gids = _pq_tile(kind, exclude_self, c32, n_, nloc, col0_,
                               jt * bm, m, lutb, qib, ctile)
            return _fold(cd, ci, d, gids, k)

        cd, ci = jax.lax.fori_loop(
            0, ntiles, tile_body,
            (jnp.full((bq, kp), jnp.inf, jnp.float32),
             jnp.full((bq, kp), -1, jnp.int32)))
        outs_d.append(cd)
        outs_i.append(ci)
    od = jnp.concatenate(outs_d, axis=0)
    oi = jnp.concatenate(outs_i, axis=0)
    return od[:b, :k], oi[:b, :k]


def scan_topk_pq(codes, lut, q_idx, col0, *, spec: tuple, k: int, n: int,
                 exclude_self: bool = False, tile_rows: int = 0):
    """Streaming top-k over a PQ-coded slab via ADC: ``codes`` [M, m]
    uint8 subspace codes (serve/quant.py), ``lut`` [B, m*256] f32 the
    per-query lookup tables (:func:`pq_lut`) → the :func:`scan_topk`
    output contract (global ids via ``col0``, masking by ``n``/local
    rows/``exclude_self``, ``(+inf, −1)`` beyond reachable).

    Distances are those of the RECONSTRUCTED (decoded) rows — a coarse
    lane by construction; callers over-fetch and f32-rescore exactly as
    for int8/int4.  Callers gate with :func:`supports_pq` (product
    specs and m > ``FUSED_MAX_PQ_M`` fall back to the engine's decode
    scan).  Dispatch and twin contract as :func:`scan_topk`."""
    m = int(codes.shape[1])
    if not supports_pq(spec, k=k, m=m):
        raise ValueError(
            f"scan_topk_pq: unsupported (spec={spec[0]!r}, k={k}, m={m}) "
            "— gate on scan_topk.supports_pq() and fall back to the "
            "two-stage decode scan")
    if lut.shape[1] != m * 256:
        raise ValueError(
            f"scan_topk_pq: lut width {lut.shape[1]} != m*256 = {m * 256}")
    kind = spec[0]
    c = 0.0 if kind == "euclidean" else spec[1]
    bm = int(tile_rows) or fused_tile_rows(
        128, jnp.uint8, k, lane="pq", pq_m=m)
    m_ = S.mode()
    if m_ == "xla":
        return _t_scan_topk_pq(codes, lut, q_idx, col0, kind=kind, c=c,
                               k=int(k), n=int(n), m=m, bm=bm,
                               exclude_self=bool(exclude_self))
    return _launch_pq(codes, lut, q_idx, col0, kind=kind, c=c, k=int(k),
                      n=int(n), m=m, bm=bm,
                      exclude_self=bool(exclude_self), mode_=m_)


# --- per-query candidate variant (the IVF probing scorer) ---------------------


def _cand_schedule(dim: int, k: int, cand: int, dtype, tile_rows: int):
    bq = _CAND_BQ
    dp = S.round_up(dim, 128)
    kp = S.round_up(k, 128)
    bm = int(tile_rows) or fused_cand_tile_rows(dim, dtype, k)
    if bm % 128:
        raise ValueError(f"tile_rows must be a multiple of 128; got {bm}")
    bm = min(bm, S.round_up(max(cand, 1), 128))
    return bq, dp, kp, bm


def _cand_pad_idq(ids, q, q_idx, bq, bm):
    """The ONE candidate-side padding recipe (kernel launcher AND twin
    — the bitwise contract depends on both consuming identical blocks):
    ids [B, C] padded with −1 (invalid), q rows zero-padded to a bq
    multiple, q_idx as the 128-lane int32 block (−1 on padded query
    rows so ``exclude_self`` can never fire on them)."""
    b, cc = ids.shape
    cp = S.round_up(cc, bm)
    bp = S.round_up(b, bq)
    ip = jnp.full((bp, cp), -1, jnp.int32)
    ip = ip.at[:b, :cc].set(jnp.asarray(ids, jnp.int32))
    qp = S.pad_rows_lanes(q, rows_to=bq)
    qip = jnp.broadcast_to(
        jnp.asarray(q_idx, jnp.int32)[:, None], (b, 128))
    if bp > b:
        qip = jnp.concatenate(
            [qip, jnp.full((bp - b, 128), -1, jnp.int32)], axis=0)
    return ip, qp, qip


def _cand_pad(rows, ids, q, q_idx, bq, bm):
    """Kernel-launcher padding: the shared id/query recipe plus the
    pre-gathered rows block (zero lanes / rows — padded id slots are
    masked by their −1 id, so their row content never matters)."""
    rp = S.pad_axis(S.pad_axis(S.pad_axis(rows, -1, 128), 1, bm), 0, bq)
    ip, qp, qip = _cand_pad_idq(ids, q, q_idx, bq, bm)
    return rp, ip, qp, qip


def _cand_body(kind: str, k: int, exclude_self: bool,
               quant: bool = False):
    def body(c_ref, q_ref, qi_ref, r_ref, id_ref, *rest):
        if quant:  # int8 rows: the gathered per-row scale block follows
            s_ref, od_ref, oi_ref, cd_scr, ci_scr = rest
        else:
            od_ref, oi_ref, cd_scr, ci_scr = rest
        jt = pl.program_id(1)

        @pl.when(jt == 0)
        def _init():
            cd_scr[:] = jnp.full_like(cd_scr, jnp.inf)
            ci_scr[:] = jnp.full_like(ci_scr, -1)

        c = c_ref[0, 0]
        q = q_ref[:].astype(jnp.float32)
        qi = qi_ref[:, :1]
        rows = r_ref[:].astype(jnp.float32)
        if quant:
            rows = rows * s_ref[:][..., None]
        ids = id_ref[:]
        d, ids = _cand_tile(kind, exclude_self, c, q, qi, rows, ids)
        skip = _prune(cd_scr[:], d, k)

        @pl.when(jnp.logical_not(skip))
        def _merge_tile():
            ncd, nci = _merge(cd_scr[:], ci_scr[:], d, ids, k)
            cd_scr[:] = ncd
            ci_scr[:] = nci

        @pl.when(jt == pl.num_programs(1) - 1)
        def _write():
            od_ref[:] = cd_scr[:]
            oi_ref[:] = ci_scr[:]

    return body


def _launch_cand(rows, ids, q, q_idx, *, kind, c, k, exclude_self, bm,
                 mode_, sc=None):
    b, cc = ids.shape
    bq, dp, kp, bm = _cand_schedule(q.shape[1], k, cc, rows.dtype, bm)
    rp, ip, qp, qip = _cand_pad(rows, ids, q, q_idx, bq, bm)
    bp, cp = ip.shape
    grid = (bp // bq, cp // bm)
    in_specs = [
        pl.BlockSpec((1, 1), lambda iq, jt: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((bq, dp), lambda iq, jt: (iq, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bq, 128), lambda iq, jt: (iq, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bq, bm, dp), lambda iq, jt: (iq, jt, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bq, bm), lambda iq, jt: (iq, jt),
                     memory_space=pltpu.VMEM),
    ]
    operands = [S.c_smem(c), qp, qip, rp, ip]
    if sc is not None:
        # gathered per-candidate dequant scales, blocked like the ids
        scp = jnp.zeros((bp, cp), jnp.float32)
        scp = scp.at[:b, :cc].set(jnp.asarray(sc, jnp.float32))
        in_specs.append(pl.BlockSpec((bq, bm), lambda iq, jt: (iq, jt),
                                     memory_space=pltpu.VMEM))
        operands.append(scp)
    od, oi = pl.pallas_call(
        _cand_body(kind, k, exclude_self, quant=sc is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bq, kp), lambda iq, jt: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, kp), lambda iq, jt: (iq, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, kp), jnp.float32),
            jax.ShapeDtypeStruct((bp, kp), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, kp), jnp.float32),
            pltpu.VMEM((bq, kp), jnp.int32),
        ],
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=S.interpret_flag(mode_),
    )(*operands)
    return od[:b, :k], oi[:b, :k]


def _t_scan_topk_cand(scan_table, cand, q, q_idx, *, kind, c, k,
                      exclude_self, bm, scale=None):
    """XLA twin of the candidate kernel: gathers each tile's rows from
    ``scan_table`` on the fly (a gather is value-exact, so this matches
    the kernel's pre-gathered stream bitwise) and folds with the shared
    functions over the identical [bq, bm, dp] block shapes."""
    b, cc = cand.shape
    bq, dp, kp, bm = _cand_schedule(q.shape[1], k, cc, scan_table.dtype, bm)
    # pad the table's feature lanes exactly like the kernel's row stream
    tp = S.pad_axis(scan_table, -1, 128)
    sf = None if scale is None else jnp.asarray(scale,
                                                jnp.float32).reshape(-1)
    ip, qp, qip = _cand_pad_idq(cand, q, q_idx, bq, bm)
    bp, cp = ip.shape
    c32 = jnp.asarray(c, jnp.float32)
    ntiles = cp // bm
    outs_d, outs_i = [], []
    for ib in range(bp // bq):
        qb = qp[ib * bq:(ib + 1) * bq].astype(jnp.float32)
        qib = qip[ib * bq:(ib + 1) * bq, :1]
        idsb = ip[ib * bq:(ib + 1) * bq]

        def tile_body(jt, carry, qb=qb, qib=qib, idsb=idsb):
            cd, ci = carry
            ids = jax.lax.dynamic_slice_in_dim(idsb, jt * bm, bm, axis=1)
            rows = tp[jnp.maximum(ids, 0)].astype(jnp.float32)
            if sf is not None:
                # same gather + in-register dequantize as the launcher's
                # pre-gathered scale stream (masked slots never read)
                rows = rows * sf[jnp.maximum(ids, 0)][..., None]
            d, ids = _cand_tile(kind, exclude_self, c32, qb, qib, rows, ids)
            return _fold(cd, ci, d, ids, k)

        cd, ci = jax.lax.fori_loop(
            0, ntiles, tile_body,
            (jnp.full((bq, kp), jnp.inf, jnp.float32),
             jnp.full((bq, kp), -1, jnp.int32)))
        outs_d.append(cd)
        outs_i.append(ci)
    od = jnp.concatenate(outs_d, axis=0)
    oi = jnp.concatenate(outs_i, axis=0)
    return od[:b, :k], oi[:b, :k]


def scan_topk_cand(scan_table, cand, q, q_idx, *, spec: tuple, k: int,
                   exclude_self: bool = False, tile_rows: int = 0,
                   scale=None):
    """Per-query-candidate streaming top-k (the IVF probing scorer):
    ``cand`` [B, C] int32 row ids into ``scan_table`` [N, D] (−1 =
    padding), ``q`` [B, D] → ``(dists f32 [B, k], ids int32 [B, k])``
    where ids are TABLE row ids.  Same carry/merge/prune machinery and
    twin contract as :func:`scan_topk`; the kernel path pre-gathers the
    [B, C, D] candidate rows (``supports_cand`` caps that footprint),
    the twin gathers per tile.  ``scale`` ([N] / [N, 1] f32): per-row
    dequant scales for an int8 ``scan_table`` — gathered with the rows
    and applied in-register (the int8 lane, serve/quant.py)."""
    if not supports_cand(spec, k=k, dim=scan_table.shape[1],
                         cand=cand.shape[1]):
        raise ValueError(
            f"scan_topk_cand: unsupported (spec={spec[0]!r}, k={k}, "
            f"C={cand.shape[1]}) — gate on scan_topk.supports_cand() "
            "and fall back to the two-stage candidate scan")
    kind = spec[0]
    c = 0.0 if kind == "euclidean" else spec[1]
    m_ = S.mode()
    if m_ == "xla":
        return _t_scan_topk_cand(scan_table, cand, q, q_idx, kind=kind,
                                 c=c, k=int(k),
                                 exclude_self=bool(exclude_self),
                                 bm=int(tile_rows), scale=scale)
    safe = jnp.maximum(jnp.asarray(cand, jnp.int32), 0)
    rows = S.pad_axis(scan_table, -1, 128)[safe]
    sc = (None if scale is None
          else jnp.asarray(scale, jnp.float32).reshape(-1)[safe])
    return _launch_cand(rows, jnp.asarray(cand, jnp.int32), q, q_idx,
                        kind=kind, c=c, k=int(k),
                        exclude_self=bool(exclude_self),
                        bm=int(tile_rows), mode_=m_, sc=sc)
