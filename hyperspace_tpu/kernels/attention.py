"""Flash-style hyperbolic attention kernel (reference CUDA kernel N7).

Scores are affine in squared Lorentz distance (Gulcehre et al. 2019 /
HyboNet),   s(q,k) = (−d²_L(q,k) + β)/τ = (2/c + 2⟨q,k⟩_L + β)/τ ,
and values aggregate to the **Lorentz centroid** (Law et al. 2019) of the
softmax weights.  Because the centroid numerator is a plain weighted sum,
the flash-attention online-softmax recurrence carries over unchanged from
the Euclidean kernel — only the epilogue differs (a Minkowski-norm
row-rescale instead of nothing).  See SURVEY.md §2 N7 and §5
"Long-context": the same recurrence, fed by ``ppermute`` instead of HBM,
is ring attention (hyperspace_tpu/parallel/ring.py).

Kernel shape: grid (batch·heads, Q blocks, KV blocks), KV innermost and
sequential; scratch carries (running max, denominator, centroid
numerator) per Q block.  Scores and accumulation are f32 regardless of
input dtype; the two matmuls per tile (Minkowski Gram, weight × V) hit
the MXU.

β and τ must be constant per (batch, head) — per-position values fall
back to the XLA twin.

**Backward (r04, VERDICT r3 #4):** a recomputing flash backward replaces
the dense-twin VJP on the kernel path.  The forward additionally emits
per-row ``lse`` (softmax log-sum-exp) and the centroid Minkowski norm;
the backward is the Lorentz-epilogue VJP (elementwise, XLA) followed by
two Pallas kernels — dq (KV inner, recomputes the score tile and
weights from lse) and dk/dv (Q inner) — so the [Nq, Nk] score matrix is
never materialized in EITHER direction: backward peak memory is
O(N·D + blocks), not O(N²).  dβ/dτ/dc fold out of per-Q-block partial
sums the dq kernel also emits.  The XLA twin (CPU / per-position β,τ)
keeps plain autodiff.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds import smath

_NEG = -1e30  # finite -inf surrogate (avoids inf-inf NaN in the recurrence)


def _t_flash_attention(q, k, v, c, beta, tau, maskf):
    """XLA twin: dense hyperbolic attention (== nn.attention.lorentz_attention).

    maskf: f32 broadcastable to [..., Nq, Nk]; > 0 means attend (the float
    carrier keeps the custom_vjp signature uniform; it is non-differentiable
    by construction).
    """
    cc = jnp.asarray(c, q.dtype)
    k_flip = k.at[..., 0].multiply(-1.0)
    gram = jnp.matmul(q, jnp.swapaxes(k_flip, -1, -2),
                      precision=jax.lax.Precision.HIGHEST)
    logits = (2.0 / cc + 2.0 * gram + beta) / tau
    if maskf is not None:
        logits = jnp.where(maskf > 0.0, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    w = jnp.where(jnp.isnan(w), 0.0, w)  # fully-masked rows
    s = jnp.matmul(w, v, precision=jax.lax.Precision.HIGHEST)
    sp = (jnp.sum(s[..., 1:] * s[..., 1:], axis=-1, keepdims=True)
          - s[..., :1] * s[..., :1])
    nrm = smath.safe_sqrt(smath.clamp_min(-sp, smath.eps_for(q.dtype)))
    return s / (smath.sqrt_c(cc) * nrm)


def _attn_body(c_ref, nk_ref, beta_ref, tau_ref, q_ref, k_ref, v_ref, o_ref,
               res_ref, m_scr, l_scr, acc_scr, *, bk: int,
               masked: bool, mask_ref=None):
    ik = pl.program_id(2)
    nk_blocks = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    c = c_ref[0, 0]
    beta = beta_ref[pl.program_id(0)]
    tau = tau_ref[pl.program_id(0)]
    nk = nk_ref[0, 0]
    q = q_ref[0].astype(jnp.float32)   # [bq, dp]
    k = k_ref[0].astype(jnp.float32)   # [bk, dp]
    v = v_ref[0].astype(jnp.float32)

    lane = jax.lax.broadcasted_iota(jnp.int32, k.shape, dimension=1)
    k_flip = jnp.where(lane == 0, -k, k)
    gram = S.dotT(q, k_flip)           # ⟨q, k⟩_L — MXU matmul 1, [bq, bk]
    logits = (2.0 / c + 2.0 * gram + beta) / tau

    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, dimension=1) + ik * bk
    valid = col < nk
    if masked:
        valid = jnp.logical_and(valid, mask_ref[0] > 0.0)
    logits = jnp.where(valid, logits, _NEG)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)
    p = jnp.where(valid, p, 0.0)       # exp(_NEG - m) underflows to 0 anyway
    l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = alpha * acc_scr[:] + jax.lax.dot_general(
        p, v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                   # MXU matmul 2
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
    acc_scr[:] = acc_new

    @pl.when(ik == nk_blocks - 1)
    def _epilogue():
        s = acc_scr[:] / jnp.maximum(l_scr[:, :1], S.MIN_NORM_F32)
        lane_o = jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=1)
        sp = jnp.sum(jnp.where(lane_o == 0, -s * s, s * s), axis=-1, keepdims=True)
        nrm = S.ksafe_sqrt(jnp.maximum(-sp, S.EPS_F32))
        sc = jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)
        o_ref[0] = (s / (sc * nrm)).astype(o_ref.dtype)
        # backward-pass residuals: log-sum-exp of the score rows (big
        # positive on fully-masked/padded rows so recomputed weights
        # underflow to 0) and the pre-normalization Minkowski norm —
        # PACKED into one [bq, 128] tile (lane 0 = lse, lanes 1+ = nrm)
        # so the per-row scalars cost one output stream, not two
        l_row = l_scr[:, :1]
        lse = jnp.where(l_row > 0.0,
                        m_scr[:, :1] + jnp.log(jnp.maximum(l_row, 1e-38)),
                        1e30)
        lane_r = jax.lax.broadcasted_iota(jnp.int32, res_ref.shape[1:],
                                          dimension=1)
        res_ref[0] = jnp.where(lane_r == 0, lse, nrm)


def _launch(q, k, v, c, beta_b, tau_b, maskf, mode_):
    """q [B, Nq, D], k/v [B, Nk, D], beta_b/tau_b [B], maskf [B, Nq, Nk]|None."""
    b, nq, d = q.shape
    nk = k.shape[1]
    dp = S.round_up(d, 128)
    bq = min(S.round_up(nq, 8), 256)
    bk = min(S.round_up(nk, 128), 512)
    # q + k + v + out + acc blocks (+ mask + logits) under the VMEM budget
    while 4 * (3 * bq * dp + 2 * bk * dp + 2 * bq * bk) > S.VMEM_BUDGET and (bq > 8 or bk > 128):
        if bk > 128 and bk >= bq:
            bk = max(128, (bk // 2) // 128 * 128)
        else:
            bq = max(8, (bq // 2) // 8 * 8)

    pad3 = lambda a, rows: S.pad_axis(S.pad_axis(a, -1, 128), -2, rows)
    qp = pad3(q, bq)
    kp = pad3(k, bk)
    vp = pad3(v, bk)
    nq_p, nk_p = qp.shape[1], kp.shape[1]
    grid = (b, nq_p // bq, nk_p // bk)

    smem = lambda idx: pl.BlockSpec((1, 1), idx, memory_space=pltpu.SMEM)
    # β/τ ride whole in SMEM as flat 1-D [B] arrays (4 B per entry; the
    # body picks its entry with program_id).  A 2-D [B, 1] SMEM window
    # pads every row to a 512 B sublane and blows the 1 MB SMEM budget
    # once B ≈ 1k (B = batch×heads at eval); Mosaic only allows rank-1
    # blocks that span the whole array, which is exactly what we want.
    per_b = pl.BlockSpec((b,), lambda ib, iq, ik: (0,),
                         memory_space=pltpu.SMEM)
    in_specs = [
        smem(lambda ib, iq, ik: (0, 0)),                   # c
        smem(lambda ib, iq, ik: (0, 0)),                   # nk
        per_b,                                             # beta
        per_b,                                             # tau
        pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0), memory_space=pltpu.VMEM),
    ]
    args = [S.c_smem(c), jnp.asarray(nk, jnp.int32).reshape(1, 1),
            beta_b.reshape(b), tau_b.reshape(b), qp, kp, vp]
    masked = maskf is not None
    if masked:
        mp = S.pad_axis(S.pad_axis(maskf.astype(jnp.float32), -1, bk), -2, bq)
        in_specs.append(pl.BlockSpec((1, bq, bk), lambda ib, iq, ik: (ib, iq, ik),
                                     memory_space=pltpu.VMEM))
        args.append(mp)

    def body(*refs):
        # layout: 4 smem + 3 vmem inputs (+ mask), 2 outs, 3 scratch
        if masked:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, mk_r, o_r, rs_r,
             m_s, l_s, a_s) = refs
        else:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, o_r, rs_r,
             m_s, l_s, a_s) = refs
            mk_r = None
        _attn_body(c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, o_r, rs_r,
                   m_s, l_s, a_s, bk=bk, masked=masked, mask_ref=mk_r)

    row_spec = pl.BlockSpec((1, bq, 128), lambda ib, iq, ik: (ib, iq, 0),
                            memory_space=pltpu.VMEM)
    out, res = pl.pallas_call(
        body,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0),
                         memory_space=pltpu.VMEM),
            row_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nq_p, dp), q.dtype),
            jax.ShapeDtypeStruct((b, nq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ],
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=S.interpret_flag(mode_),
    )(*args)
    return out[:, :nq, :d], res[:, :, 0], res[:, :, 1]


def _scalar_per_batch(x, lead, dtype):
    """Broadcast a per-(batch, head) scalar spec (e.g. [h, 1, 1]) to [B]."""
    arr = jnp.asarray(x, dtype)
    return jnp.broadcast_to(arr, lead + (1, 1))[..., 0, 0].reshape(-1)


# --- recomputing flash backward (module doc) ----------------------------------


def _score_tile(c, beta, tau, q, k, nk, ik, bk, masked, mask_ref):
    """Recompute one [bq, bk] score tile + validity (shared by both
    backward kernels; identical math to the forward body)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, k.shape, dimension=1)
    k_flip = jnp.where(lane == 0, -k, k)
    gram = S.dotT(q, k_flip)
    sigma = (2.0 / c + 2.0 * gram + beta) / tau
    col = jax.lax.broadcasted_iota(jnp.int32, sigma.shape, dimension=1) + ik * bk
    valid = col < nk
    if masked:
        valid = jnp.logical_and(valid, mask_ref[0] > 0.0)
    return sigma, valid, k_flip


def _dq_body(c_ref, nk_ref, beta_ref, tau_ref, q_ref, k_ref, v_ref, dsp_ref,
             ld_ref, dq_ref, dst_ref, dq_scr, part_scr,
             *, bk: int, masked: bool, mask_ref=None):
    ik = pl.program_id(2)
    nk_blocks = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        part_scr[:] = jnp.zeros_like(part_scr)

    c = c_ref[0, 0]
    beta = beta_ref[pl.program_id(0)]
    tau = tau_ref[pl.program_id(0)]
    nk = nk_ref[0, 0]
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    dsp = dsp_ref[0].astype(jnp.float32)
    lse = ld_ref[0][:, :1]       # packed per-row scalars: lane 0 = lse,
    di = ld_ref[0][:, 1:2]       # lane 1 = di (one stream, not two)

    sigma, valid, k_flip = _score_tile(c, beta, tau, q, k, nk, ik, bk,
                                       masked, mask_ref)
    p = jnp.where(valid, jnp.exp(sigma - lse), 0.0)
    dv_dot = S.dotT(dsp, v)                       # ⟨dsp_i, v_j⟩, MXU
    dsig = jnp.where(valid, p * (dv_dot - di), 0.0)
    dq_scr[:] += (2.0 / tau) * jax.lax.dot_general(
        dsig, k_flip, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    # dτ partial Σ dσ·σ accumulates as a (8, 128)-tiled broadcast (a
    # scalar-shaped output block fails the Mosaic (8, 128) tiling rule).
    # dβ needs no partial: Σ_j dσ_ij = 0 exactly (softmax shift
    # invariance), so dβ ≡ 0 and the score-offset dc term vanishes too.
    part_scr[:] += jnp.sum(jnp.where(valid, dsig * sigma, 0.0))

    @pl.when(ik == nk_blocks - 1)
    def _write():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)
        dst_ref[0, 0] = part_scr[:]


def _dkv_body(c_ref, nk_ref, beta_ref, tau_ref, q_ref, k_ref, v_ref, dsp_ref,
              ld_ref, dk_ref, dv_ref, dk_scr, dv_scr,
              *, bk: int, masked: bool, mask_ref=None):
    iq = pl.program_id(2)
    nq_blocks = pl.num_programs(2)
    ik = pl.program_id(1)          # KV block index is the OUTER grid dim

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    c = c_ref[0, 0]
    beta = beta_ref[pl.program_id(0)]
    tau = tau_ref[pl.program_id(0)]
    nk = nk_ref[0, 0]
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    dsp = dsp_ref[0].astype(jnp.float32)
    lse = ld_ref[0][:, :1]       # packed: lane 0 = lse, lane 1 = di
    di = ld_ref[0][:, 1:2]

    sigma, valid, _ = _score_tile(c, beta, tau, q, k, nk, ik, bk,
                                  masked, mask_ref)
    p = jnp.where(valid, jnp.exp(sigma - lse), 0.0)
    dv_scr[:] += jax.lax.dot_general(                 # pᵀ @ dsp
        p, dsp, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    dv_dot = S.dotT(dsp, v)
    dsig = jnp.where(valid, p * (dv_dot - di), 0.0)
    lane_q = jax.lax.broadcasted_iota(jnp.int32, q.shape, dimension=1)
    q_flip = jnp.where(lane_q == 0, -q, q)
    dk_scr[:] += (2.0 / tau) * jax.lax.dot_general(   # dsigᵀ @ (J q)
        dsig, q_flip, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)

    @pl.when(iq == nq_blocks - 1)
    def _write():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_blocks(nq, nk, dp):
    bq = min(S.round_up(nq, 8), 256)
    bk = min(S.round_up(nk, 128), 512)
    # q + k + v + dsp + dq/dkv scratch + lse/di + score tiles
    while 4 * (6 * bq * dp + 4 * bk * dp + 3 * bq * bk) > S.VMEM_BUDGET and (
            bq > 8 or bk > 128):
        if bk > 128 and bk >= bq:
            bk = max(128, (bk // 2) // 128 * 128)
        else:
            bq = max(8, (bq // 2) // 8 * 8)
    return bq, bk


def _bwd_launch(q, k, v, c, beta_b, tau_b, maskf, dsp, lse, di, mode_):
    """Run both backward kernels; returns (dq, dk, dv, dst [B])."""
    b, nq, d = q.shape
    nk = k.shape[1]
    dp = S.round_up(d, 128)
    bq, bk = _bwd_blocks(nq, nk, dp)
    pad3 = lambda a, rows: S.pad_axis(S.pad_axis(a, -1, 128), -2, rows)
    qp, kp, vp = pad3(q, bq), pad3(k, bk), pad3(v, bk)
    dspp = pad3(dsp, bq)
    nq_p, nk_p = qp.shape[1], kp.shape[1]
    # rows the BACKWARD padding adds beyond the forward-padded length
    # carry the fully-masked 1e30 sentinel (ADVICE r04): lse = 0 there
    # would make p = exp(sigma - 0) overflow and 0·inf = NaN poison
    # dk/dv through the column sums; with the sentinel p underflows to 0
    pad_rows = max(nq_p - lse.shape[1], 0)
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_rows)),
                    constant_values=1e30)[:, :nq_p]
    di_p = S.pad_axis(di, -1, bq)[:, :nq_p]
    # per-row scalars ride PACKED in one [B, nq_p, 128] stream (lane 0 =
    # lse, lane 1 = di) — halves the broadcast residual bytes vs two
    # full-lane arrays (ADVICE r04)
    lane128 = jnp.arange(128)[None, None, :]
    ld_b = jnp.where(lane128 == 0, lse_p[..., None], di_p[..., None])

    smem = lambda idx: pl.BlockSpec((1, 1), idx, memory_space=pltpu.SMEM)
    per_b = lambda: pl.BlockSpec((b,), lambda ib, i1, i2: (0,),
                                 memory_space=pltpu.SMEM)
    base_args = [S.c_smem(c), jnp.asarray(nk, jnp.int32).reshape(1, 1),
                 beta_b.reshape(b), tau_b.reshape(b)]
    masked = maskf is not None
    mp = None
    if masked:
        mp = S.pad_axis(S.pad_axis(maskf.astype(jnp.float32), -1, bk), -2, bq)

    # dq kernel: grid (B, Qb, KVb), KV inner
    in_specs = [
        smem(lambda ib, iq, ik: (0, 0)),
        smem(lambda ib, iq, ik: (0, 0)),
        per_b(),
        per_b(),
        pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0)),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0)),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0)),
        pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0)),
        pl.BlockSpec((1, bq, 128), lambda ib, iq, ik: (ib, iq, 0)),
    ]
    args = base_args + [qp, kp, vp, dspp, ld_b]
    if masked:
        in_specs.append(pl.BlockSpec((1, bq, bk),
                                     lambda ib, iq, ik: (ib, iq, ik)))
        args.append(mp)

    def dq_kernel(*refs):
        if masked:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r, mk_r,
             dq_r, st_r, dq_s, pt_s) = refs
        else:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r,
             dq_r, st_r, dq_s, pt_s) = refs
            mk_r = None
        _dq_body(c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r,
                 dq_r, st_r, dq_s, pt_s, bk=bk, masked=masked,
                 mask_ref=mk_r)

    nqb, nkb = nq_p // bq, nk_p // bk
    dq, dst = pl.pallas_call(
        dq_kernel,
        grid=(b, nqb, nkb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0)),
            pl.BlockSpec((1, 1, 8, 128), lambda ib, iq, ik: (ib, iq, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nq_p, dp), jnp.float32),
            jax.ShapeDtypeStruct((b, nqb, 8, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dp), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
        ],
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=S.interpret_flag(mode_),
    )(*args)

    # dkv kernel: grid (B, KVb, Qb), Q inner
    in_specs2 = [
        smem(lambda ib, ik, iq: (0, 0)),
        smem(lambda ib, ik, iq: (0, 0)),
        per_b(),
        per_b(),
        pl.BlockSpec((1, bq, dp), lambda ib, ik, iq: (ib, iq, 0)),
        pl.BlockSpec((1, bk, dp), lambda ib, ik, iq: (ib, ik, 0)),
        pl.BlockSpec((1, bk, dp), lambda ib, ik, iq: (ib, ik, 0)),
        pl.BlockSpec((1, bq, dp), lambda ib, ik, iq: (ib, iq, 0)),
        pl.BlockSpec((1, bq, 128), lambda ib, ik, iq: (ib, iq, 0)),
    ]
    args2 = base_args + [qp, kp, vp, dspp, ld_b]
    if masked:
        in_specs2.append(pl.BlockSpec((1, bq, bk),
                                      lambda ib, ik, iq: (ib, iq, ik)))
        args2.append(mp)

    def dkv_kernel(*refs):
        if masked:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r, mk_r,
             dk_r, dv_r, dk_s, dv_s) = refs
        else:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r,
             dk_r, dv_r, dk_s, dv_s) = refs
            mk_r = None
        _dkv_body(c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, ds_r, ld_r,
                  dk_r, dv_r, dk_s, dv_s, bk=bk, masked=masked,
                  mask_ref=mk_r)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, nkb, nqb),
        in_specs=in_specs2,
        out_specs=[
            pl.BlockSpec((1, bk, dp), lambda ib, ik, iq: (ib, ik, 0)),
            pl.BlockSpec((1, bk, dp), lambda ib, ik, iq: (ib, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nk_p, dp), jnp.float32),
            jax.ShapeDtypeStruct((b, nk_p, dp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dp), jnp.float32),
            pltpu.VMEM((bk, dp), jnp.float32),
        ],
        compiler_params=S.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=S.interpret_flag(mode_),
    )(*args2)
    return (dq[:, :nq, :d], dk[:, :nk, :d], dv[:, :nk, :d],
            jnp.sum(dst[:, :, 0, 0], axis=1))


def _epilogue_jax(s, c):
    """Exact XLA mirror of the kernel epilogue (same clamps) — the
    elementwise piece of the backward runs through its autodiff."""
    lane0 = s[..., :1]
    sp = jnp.sum(s[..., 1:] * s[..., 1:], axis=-1, keepdims=True) - lane0 * lane0
    nrm = S.ksafe_sqrt(jnp.maximum(-sp, S.EPS_F32))
    sc = jnp.maximum(S.ksafe_sqrt(jnp.asarray(c, jnp.float32)), S.MIN_NORM_F32)
    return s / (sc * nrm)


@jax.custom_vjp
def _flash3(q3, k3, v3, c, beta_b, tau_b, maskf, mode_s):
    out, _, _ = _launch(q3, k3, v3, c, beta_b, tau_b, maskf,
                        "interpret" if mode_s.shape[0] else "pallas")
    return out


def _fa3_fwd(q3, k3, v3, c, beta_b, tau_b, maskf, mode_s):
    mode_ = "interpret" if mode_s.shape[0] else "pallas"
    out, lse, nrm = _launch(q3, k3, v3, c, beta_b, tau_b, maskf, mode_)
    return out, (q3, k3, v3, c, beta_b, tau_b, maskf, out, lse, nrm, mode_s)


def _fa3_bwd(res, g):
    q3, k3, v3, c, beta_b, tau_b, maskf, out, lse, nrm, mode_s = res
    mode_ = "interpret" if mode_s.shape[0] else "pallas"
    nq = q3.shape[1]
    c32 = jnp.asarray(c, jnp.float32)
    sc = jnp.maximum(S.ksafe_sqrt(c32), S.MIN_NORM_F32)
    s_pre = out.astype(jnp.float32) * (sc * nrm[:, :nq, None])
    # elementwise Lorentz-normalize epilogue: XLA autodiff
    _, epi_vjp = jax.vjp(_epilogue_jax, s_pre, c32)
    dsp, dc_epi = epi_vjp(g.astype(jnp.float32))
    di = jnp.sum(dsp * s_pre, axis=-1)                      # [B, nq]
    dq, dk, dv, dst = _bwd_launch(q3, k3, v3, c, beta_b, tau_b, maskf,
                                  dsp, lse, di, mode_)
    # β shifts every logit of a softmax row uniformly → dβ ≡ 0 exactly,
    # and the same row-sum identity kills the score-offset dc term; the
    # only c gradient is the epilogue's
    dbeta = jnp.zeros_like(beta_b)
    dtau = -dst / tau_b
    dc = dc_epi.astype(jnp.float32)
    dmask = None if maskf is None else jnp.zeros_like(maskf)
    return (dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype),
            dc, dbeta, dtau, dmask, None)


_flash3.defvjp(_fa3_fwd, _fa3_bwd)


def flash_attention(q, k, v, c, *, beta=0.0, tau=1.0, mask=None):
    """Hyperbolic flash attention (kernel N7); see module docstring.

    q: [..., Nq, D], k/v: [..., Nk, D] hyperboloid points; beta/tau scalars
    or [..., 1, 1]-shaped per-(batch, head) arrays; mask: bool/float
    broadcastable to [..., Nq, Nk], truthy = attend.  Returns hyperboloid
    points [..., Nq, D].  On the kernel path BOTH directions are flash
    (forward online-softmax, recomputing backward); the XLA twin serves
    CPU and per-position β/τ with plain autodiff.
    """
    maskf = None if mask is None else jax.lax.stop_gradient(
        jnp.asarray(mask, jnp.float32))
    mode_ = S.mode()
    bshape = jnp.shape(beta)
    tshape = jnp.shape(tau)
    per_pos = (bshape[-2:] not in ((), (1, 1)) and len(bshape) >= 2) or (
        tshape[-2:] not in ((), (1, 1)) and len(tshape) >= 2)
    if mode_ == "xla" or per_pos:
        return _t_flash_attention(q, k, v, c, beta, tau, maskf)
    # 3-D reshape/broadcast happens OUTSIDE the custom_vjp boundary, so
    # autodiff sums the k/v/β/τ cotangents over broadcast dims for free
    lead = q.shape[:-2]
    bsz = 1
    for s_ in lead:
        bsz *= s_
    q3 = q.reshape((bsz,) + q.shape[-2:])
    k3 = jnp.broadcast_to(k, lead + k.shape[-2:]).reshape((bsz,) + k.shape[-2:])
    v3 = jnp.broadcast_to(v, lead + v.shape[-2:]).reshape((bsz,) + v.shape[-2:])
    beta_b = _scalar_per_batch(beta, lead, jnp.float32)
    tau_b = _scalar_per_batch(tau, lead, jnp.float32)
    if maskf is not None:
        maskf = jnp.broadcast_to(
            maskf, lead + (q.shape[-2], k.shape[-2])
        ).reshape((bsz,) + (q.shape[-2], k.shape[-2]))
    # static mode flag rides as an empty/1-element dummy int array (shape
    # is static under jit — and int dtype means a None cotangent is valid)
    mode_s = jnp.zeros((1 if mode_ == "interpret" else 0,), jnp.int32)
    out = _flash3(q3, k3, v3, c, beta_b, tau_b, maskf, mode_s)
    return out.reshape(lead + out.shape[-2:])
