"""Flash-style hyperbolic attention kernel (reference CUDA kernel N7).

Scores are affine in squared Lorentz distance (Gulcehre et al. 2019 /
HyboNet),   s(q,k) = (−d²_L(q,k) + β)/τ = (2/c + 2⟨q,k⟩_L + β)/τ ,
and values aggregate to the **Lorentz centroid** (Law et al. 2019) of the
softmax weights.  Because the centroid numerator is a plain weighted sum,
the flash-attention online-softmax recurrence carries over unchanged from
the Euclidean kernel — only the epilogue differs (a Minkowski-norm
row-rescale instead of nothing).  See SURVEY.md §2 N7 and §5
"Long-context": the same recurrence, fed by ``ppermute`` instead of HBM,
is ring attention (hyperspace_tpu/parallel/ring.py).

Kernel shape: grid (batch·heads, Q blocks, KV blocks), KV innermost and
sequential; scratch carries (running max, denominator, centroid
numerator) per Q block.  Scores and accumulation are f32 regardless of
input dtype; the two matmuls per tile (Minkowski Gram, weight × V) hit
the MXU.

β and τ must be constant per (batch, head) — per-position values fall
back to the XLA twin.  Gradients always flow through the twin
(rematerializing custom_vjp, like every kernel in this package).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds import smath

_NEG = -1e30  # finite -inf surrogate (avoids inf-inf NaN in the recurrence)


def _t_flash_attention(q, k, v, c, beta, tau, maskf):
    """XLA twin: dense hyperbolic attention (== nn.attention.lorentz_attention).

    maskf: f32 broadcastable to [..., Nq, Nk]; > 0 means attend (the float
    carrier keeps the custom_vjp signature uniform; it is non-differentiable
    by construction).
    """
    cc = jnp.asarray(c, q.dtype)
    k_flip = k.at[..., 0].multiply(-1.0)
    gram = jnp.matmul(q, jnp.swapaxes(k_flip, -1, -2),
                      precision=jax.lax.Precision.HIGHEST)
    logits = (2.0 / cc + 2.0 * gram + beta) / tau
    if maskf is not None:
        logits = jnp.where(maskf > 0.0, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    w = jnp.where(jnp.isnan(w), 0.0, w)  # fully-masked rows
    s = jnp.matmul(w, v, precision=jax.lax.Precision.HIGHEST)
    sp = (jnp.sum(s[..., 1:] * s[..., 1:], axis=-1, keepdims=True)
          - s[..., :1] * s[..., :1])
    nrm = smath.safe_sqrt(smath.clamp_min(-sp, smath.eps_for(q.dtype)))
    return s / (smath.sqrt_c(cc) * nrm)


def _attn_body(c_ref, nk_ref, beta_ref, tau_ref, q_ref, k_ref, v_ref, o_ref,
               m_scr, l_scr, acc_scr, *, bk: int, masked: bool, mask_ref=None):
    ik = pl.program_id(2)
    nk_blocks = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    c = c_ref[0, 0]
    beta = beta_ref[pl.program_id(0)]
    tau = tau_ref[pl.program_id(0)]
    nk = nk_ref[0, 0]
    q = q_ref[0].astype(jnp.float32)   # [bq, dp]
    k = k_ref[0].astype(jnp.float32)   # [bk, dp]
    v = v_ref[0].astype(jnp.float32)

    lane = jax.lax.broadcasted_iota(jnp.int32, k.shape, dimension=1)
    k_flip = jnp.where(lane == 0, -k, k)
    gram = S.dotT(q, k_flip)           # ⟨q, k⟩_L — MXU matmul 1, [bq, bk]
    logits = (2.0 / c + 2.0 * gram + beta) / tau

    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, dimension=1) + ik * bk
    valid = col < nk
    if masked:
        valid = jnp.logical_and(valid, mask_ref[0] > 0.0)
    logits = jnp.where(valid, logits, _NEG)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)
    p = jnp.where(valid, p, 0.0)       # exp(_NEG - m) underflows to 0 anyway
    l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = alpha * acc_scr[:] + jax.lax.dot_general(
        p, v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                   # MXU matmul 2
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
    acc_scr[:] = acc_new

    @pl.when(ik == nk_blocks - 1)
    def _epilogue():
        s = acc_scr[:] / jnp.maximum(l_scr[:, :1], S.MIN_NORM_F32)
        lane_o = jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=1)
        sp = jnp.sum(jnp.where(lane_o == 0, -s * s, s * s), axis=-1, keepdims=True)
        nrm = S.ksafe_sqrt(jnp.maximum(-sp, S.EPS_F32))
        sc = jnp.maximum(S.ksafe_sqrt(c), S.MIN_NORM_F32)
        o_ref[0] = (s / (sc * nrm)).astype(o_ref.dtype)


def _launch(q, k, v, c, beta_b, tau_b, maskf, mode_):
    """q [B, Nq, D], k/v [B, Nk, D], beta_b/tau_b [B], maskf [B, Nq, Nk]|None."""
    b, nq, d = q.shape
    nk = k.shape[1]
    dp = S.round_up(d, 128)
    bq = min(S.round_up(nq, 8), 256)
    bk = min(S.round_up(nk, 128), 512)
    # q + k + v + out + acc blocks (+ mask + logits) under the VMEM budget
    while 4 * (3 * bq * dp + 2 * bk * dp + 2 * bq * bk) > S.VMEM_BUDGET and (bq > 8 or bk > 128):
        if bk > 128 and bk >= bq:
            bk = max(128, (bk // 2) // 128 * 128)
        else:
            bq = max(8, (bq // 2) // 8 * 8)

    pad3 = lambda a, rows: S.pad_axis(S.pad_axis(a, -1, 128), -2, rows)
    qp = pad3(q, bq)
    kp = pad3(k, bk)
    vp = pad3(v, bk)
    nq_p, nk_p = qp.shape[1], kp.shape[1]
    grid = (b, nq_p // bq, nk_p // bk)

    smem = lambda idx: pl.BlockSpec((1, 1), idx, memory_space=pltpu.SMEM)
    # β/τ ride whole in SMEM as flat 1-D [B] arrays (4 B per entry; the
    # body picks its entry with program_id).  A 2-D [B, 1] SMEM window
    # pads every row to a 512 B sublane and blows the 1 MB SMEM budget
    # once B ≈ 1k (B = batch×heads at eval); Mosaic only allows rank-1
    # blocks that span the whole array, which is exactly what we want.
    per_b = pl.BlockSpec((b,), lambda ib, iq, ik: (0,),
                         memory_space=pltpu.SMEM)
    in_specs = [
        smem(lambda ib, iq, ik: (0, 0)),                   # c
        smem(lambda ib, iq, ik: (0, 0)),                   # nk
        per_b,                                             # beta
        per_b,                                             # tau
        pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dp), lambda ib, iq, ik: (ib, ik, 0), memory_space=pltpu.VMEM),
    ]
    args = [S.c_smem(c), jnp.asarray(nk, jnp.int32).reshape(1, 1),
            beta_b.reshape(b), tau_b.reshape(b), qp, kp, vp]
    masked = maskf is not None
    if masked:
        mp = S.pad_axis(S.pad_axis(maskf.astype(jnp.float32), -1, bk), -2, bq)
        in_specs.append(pl.BlockSpec((1, bq, bk), lambda ib, iq, ik: (ib, iq, ik),
                                     memory_space=pltpu.VMEM))
        args.append(mp)

    def body(*refs):
        # layout: 4 smem + 3 vmem inputs (+ mask), out, 3 scratch
        if masked:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, mk_r, o_r, m_s, l_s, a_s) = refs
        else:
            (c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, o_r, m_s, l_s, a_s) = refs
            mk_r = None
        _attn_body(c_r, nk_r, be_r, ta_r, q_r, k_r, v_r, o_r, m_s, l_s, a_s,
                   bk=bk, masked=masked, mask_ref=mk_r)

    out = pl.pallas_call(
        body,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, dp), lambda ib, iq, ik: (ib, iq, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, nq_p, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=S.interpret_flag(mode_),
    )(*args)
    return out[:, :nq, :d]


def _scalar_per_batch(x, lead, dtype):
    """Broadcast a per-(batch, head) scalar spec (e.g. [h, 1, 1]) to [B]."""
    arr = jnp.asarray(x, dtype)
    return jnp.broadcast_to(arr, lead + (1, 1))[..., 0, 0].reshape(-1)


def _fwd_impl(q, k, v, c, beta, tau, maskf):
    mode_ = S.mode()
    if mode_ == "xla":
        return _t_flash_attention(q, k, v, c, beta, tau, maskf)
    lead = q.shape[:-2]
    bshape = jnp.shape(beta)
    tshape = jnp.shape(tau)
    # per-position β/τ (trailing dims not all 1) → twin
    if (bshape[-2:] not in ((), (1, 1)) and len(bshape) >= 2) or (
            tshape[-2:] not in ((), (1, 1)) and len(tshape) >= 2):
        return _t_flash_attention(q, k, v, c, beta, tau, maskf)
    bsz = 1
    for s in lead:
        bsz *= s
    q3 = q.reshape((bsz,) + q.shape[-2:])
    k3 = jnp.broadcast_to(k, lead + k.shape[-2:]).reshape((bsz,) + k.shape[-2:])
    v3 = jnp.broadcast_to(v, lead + v.shape[-2:]).reshape((bsz,) + v.shape[-2:])
    beta_b = _scalar_per_batch(beta, lead, jnp.float32)
    tau_b = _scalar_per_batch(tau, lead, jnp.float32)
    if maskf is not None:
        maskf = jnp.broadcast_to(
            maskf, lead + (q.shape[-2], k.shape[-2])
        ).reshape((bsz,) + (q.shape[-2], k.shape[-2]))
    out = _launch(q3, k3, v3, c, beta_b, tau_b, maskf, mode_)
    return out.reshape(lead + out.shape[-2:])


@jax.custom_vjp
def _flash_attention_vjp(q, k, v, c, beta, tau, maskf):
    return _fwd_impl(q, k, v, c, beta, tau, maskf)


def _fa_fwd(q, k, v, c, beta, tau, maskf):
    return _fwd_impl(q, k, v, c, beta, tau, maskf), (q, k, v, c, beta, tau, maskf)


def _fa_bwd(res, g):
    _, vjp = jax.vjp(_t_flash_attention, *res)
    return vjp(g)


_flash_attention_vjp.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, c, *, beta=0.0, tau=1.0, mask=None):
    """Hyperbolic flash attention (kernel N7); see module docstring.

    q: [..., Nq, D], k/v: [..., Nk, D] hyperboloid points; beta/tau scalars
    or [..., 1, 1]-shaped per-(batch, head) arrays; mask: bool/float
    broadcastable to [..., Nq, Nk], truthy = attend.  Returns hyperboloid
    points [..., Nq, D].
    """
    maskf = None if mask is None else jax.lax.stop_gradient(
        jnp.asarray(mask, jnp.float32))
    return _flash_attention_vjp(q, k, v, c, beta, tau, maskf)
