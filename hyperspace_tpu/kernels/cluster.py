"""Cluster-pair SpMM aggregation — kill the [E, F] message round-trip.

The r02 anatomy probes (docs/benchmarks.md) showed the aggregation's
gather (`w·h[senders]`) is latency-bound and the block-CSR scatter reads
the materialized [E, F] messages back from HBM: every pass pays ~2·E·F
bytes of HBM traffic that exists only because the gather and the scatter
are separate XLA/Pallas ops.

This kernel processes edges grouped by (receiver-block, sender-block)
pairs and never materializes messages: with both endpoint blocks resident
in VMEM, a 128-edge sub-chunk becomes two MXU matmuls

    out_tile  +=  A @ (B @ h_tile)
    A[r_loc, e] = w_e      (edge-weighted receiver one-hot, [bn, 128])
    B[e, s_loc] = 1        (sender one-hot, [128, bs])

so HBM traffic is one h-tile load per (rb, sb) pair plus the edge id/
weight stream — for edges with block locality that is a fraction of
E·F.  Low-density pairs would waste a whole tile load on a few edges, so
the host splitter (`build_cluster_split`) routes only pairs with
``>= min_pair_edges`` through this kernel; the rest ("stragglers") keep
the existing gather + block-CSR path.  For a symmetrized edge list the
pair (a, b) and its mirror (b, a) have equal edge counts, so the split
is closed under edge reversal and the involution backward
(nn/scatter.py) survives on both paths.

Exactness: B@h is a pure row selection (each edge row has exactly one 1,
so no two nonzeros ever sum) — in bf16 the products and single-term sums
are exact, which is why the bf16 path can use the fast single-pass MXU
mode; accumulation is f32 throughout.  f32 inputs use HIGHEST precision
like kernels/segment.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S

_BN = 256   # receiver-block rows (output tile)
_BS = 256   # sender-block rows (h tile).  bs=128/thr=64 wins the
# ISOLATED forward aggregation (24.1 vs 29.4 ms — smaller tiles make
# ~200-edge pairs profitable) but LOSES the full train step (0.146 vs
# 0.136 s clean-chip): in the full step XLA overlaps the straggler
# gather chain with other work, so shrinking it saves nothing while the
# larger cluster grid adds serial time.  Full-step wins set the default.
_BK = 512   # edges per chunk


class ClusterPlan(NamedTuple):
    """Work-item schedule for :func:`cluster_aggregate` (host-built).

    Items are receiver-block-major; ``first`` marks each rb's first item
    (the kernel zeroes the output tile there).  Every receiver block gets
    at least one item even if it owns no clustered edge.  ``first_chunk``
    marks the first item touching each edge CHUNK — the edge-aligned
    output of :func:`cluster_sddmm` zeroes its chunk block there (a
    boundary chunk is visited by two pairs and must accumulate).
    """

    rb: np.ndarray     # [T] item -> receiver-block index
    sb: np.ndarray     # [T] item -> sender-block index
    chunk: np.ndarray  # [T] item -> edge-chunk index
    first: np.ndarray  # [T] 1 iff first item of its receiver block
    first_chunk: np.ndarray  # [T] 1 iff first item of its edge chunk


def build_cluster_plan(
    receivers: np.ndarray,  # [E] sorted by (rb, sb) within the clustered set
    senders: np.ndarray,    # [E] aligned
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> ClusterPlan:
    """Plan (rb, sb, chunk) items over edges pre-sorted by (rb, sb).

    Boundary chunks shared by two pairs are loaded by both and masked by
    the in-kernel local-range test (same trick as kernels/segment.py).
    """
    r = np.asarray(receivers)
    s = np.asarray(senders)
    e_pad = S.round_up(max(len(r), 1), bk)
    nchunks = e_pad // bk
    nb = -(-num_nodes // bn)
    key = (r // bn).astype(np.int64) * ((num_nodes // bs) + 1) + s // bs
    if len(key) > 1 and not np.all(np.diff(key) >= 0):
        raise ValueError("cluster plan needs edges sorted by (rb, sb)")
    # pair boundaries
    starts = np.flatnonzero(np.r_[True, key[1:] != key[:-1]]) if len(key) else np.zeros(0, np.int64)
    ends = np.r_[starts[1:], len(key)] if len(starts) else starts
    p_rb = (r[starts] // bn).astype(np.int32) if len(starts) else np.zeros(0, np.int32)
    p_sb = (s[starts] // bs).astype(np.int32) if len(starts) else np.zeros(0, np.int32)
    c0 = np.minimum(starts // bk, nchunks - 1)
    c1 = np.clip(-(-ends // bk), c0 + 1, nchunks)
    counts = (c1 - c0).astype(np.int64)

    rb_items = np.repeat(p_rb, counts)
    sb_items = np.repeat(p_sb, counts)
    chunk_items = (np.arange(counts.sum(), dtype=np.int64)
                   - np.repeat(np.cumsum(counts) - counts, counts)
                   + np.repeat(c0, counts)).astype(np.int32)

    # every receiver block needs >= 1 item so its output tile is zeroed;
    # dummy items point at chunk 0 whose edges (some other pair's) fail
    # the local-range test and contribute nothing
    present = np.zeros(nb, bool)
    present[p_rb] = True
    missing = np.flatnonzero(~present).astype(np.int32)
    rb_items = np.concatenate([rb_items, missing])
    sb_items = np.concatenate([sb_items, np.zeros(len(missing), np.int32)])
    chunk_items = np.concatenate([chunk_items, np.zeros(len(missing), np.int32)])

    order = np.argsort(rb_items, kind="stable")
    rb_items = rb_items[order].astype(np.int32)
    sb_items = sb_items[order].astype(np.int32)
    chunk_items = chunk_items[order].astype(np.int32)
    first = np.zeros(len(rb_items), np.int32)
    first[np.flatnonzero(np.r_[True, rb_items[1:] != rb_items[:-1]])] = 1
    first_chunk = np.zeros(len(chunk_items), np.int32)
    _, idx0 = np.unique(chunk_items, return_index=True)
    first_chunk[idx0] = 1
    return ClusterPlan(rb_items, sb_items, chunk_items, first, first_chunk)


def _body(bn: int, bs: int, fast_bf16: bool):
    prec = None if fast_bf16 else jax.lax.Precision.HIGHEST
    dt = jnp.bfloat16 if fast_bf16 else jnp.float32

    def body(rb_ref, sb_ref, chk_ref, first_ref, r_ref, s_ref, w_ref,
             h_ref, o_ref):
        t = pl.program_id(0)
        rb = rb_ref[t]
        sb = sb_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        r = r_ref[0]                    # [bk//128, 128] int32 (global)
        s = s_ref[0]
        w = w_ref[0].astype(jnp.float32)
        h_t = h_ref[:].astype(dt)       # [bs, F]
        acc = jnp.zeros_like(o_ref[:], jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (128, bs), 1)
        for j in range(r.shape[0]):
            ls = s[j] - sb * bs          # [128]; out-of-range matches nothing
            lr = r[j] - rb * bn
            b_oh = (cols == ls[:, None]).astype(dt)          # [128, bs]
            tmp = jnp.dot(b_oh, h_t, preferred_element_type=jnp.float32,
                          precision=prec)                    # [128, F] exact
            a_w = jnp.where(rows == lr[None, :], w[j][None, :], 0.0)
            acc += jnp.dot(a_w.astype(dt), tmp.astype(dt),
                           preferred_element_type=jnp.float32, precision=prec)
        o_ref[:] += acc

    return body


def cluster_aggregate(
    h: jax.Array,          # [N, F] node values
    w: jax.Array,          # [E] edge weights (0 on padding/masked edges)
    receivers: jax.Array,  # [E] int32 global, sorted by (rb, sb)
    senders: jax.Array,    # [E] int32 global, aligned
    plan: tuple,           # ClusterPlan device arrays (rb, sb, chunk, first)
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e] without ever
    materializing [E, F] messages.  Twin/oracle: ``segment_sum`` of the
    gathered messages (any receiver order)."""
    m = S.mode()
    if m == "xla":
        acc_dt = jnp.promote_types(h.dtype, jnp.float32)
        msgs = (w[:, None] * h[senders]).astype(acc_dt)
        return jax.ops.segment_sum(msgs, receivers, num_nodes).astype(h.dtype)
    e = receivers.shape[0]
    if e == 0:
        # an empty clustered set still carries one dummy plan item per
        # receiver block; skipping the kernel (sum of nothing = 0) avoids
        # indexing chunk 0 of a zero-chunk edge array
        return jnp.zeros((num_nodes, h.shape[-1]), h.dtype)
    f = h.shape[-1]
    fp = S.round_up(f, 128)
    n_pad = S.round_up(num_nodes, max(bn, bs))
    h_p = S.pad_axis(S.pad_axis(h, -1, 128), 0, max(bn, bs))
    e_pad = S.round_up(e, bk)
    # pad ids out-of-range so padded lanes match no local row
    pad_ids = lambda a: jnp.pad(a, (0, e_pad - e), constant_values=n_pad)
    r2d = pad_ids(receivers).reshape(e_pad // bk, bk // 128, 128)
    s2d = pad_ids(senders).reshape(e_pad // bk, bk // 128, 128)
    w2d = jnp.pad(w.astype(jnp.float32), (0, e_pad - e)).reshape(
        e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    fast_bf16 = h.dtype == jnp.bfloat16
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((bs, fp), lambda t, rb, sb, chk, first: (sb[t], 0)),
        ],
        out_specs=pl.BlockSpec((bn, fp),
                               lambda t, rb, sb, chk, first: (rb[t], 0)),
    )
    out = pl.pallas_call(
        _body(bn, bs, fast_bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S.round_up(n_pad, bn), fp),
                                       jnp.float32),
        interpret=S.interpret_flag(m),
    )(*tuple(plan)[:4], r2d, s2d, w2d, h_p)
    return out[:num_nodes, :f].astype(h.dtype)


# --- cluster SDDMM: per-edge <g[r], h[s]> without [E, F] gathers --------------


def _sddmm_body(bn: int, bs: int, fast_bf16: bool):
    prec = None if fast_bf16 else jax.lax.Precision.HIGHEST
    dt = jnp.bfloat16 if fast_bf16 else jnp.float32

    def body(rb_ref, sb_ref, chk_ref, firstc_ref, r_ref, s_ref,
             g_ref, h_ref, o_ref):
        t = pl.program_id(0)
        rb = rb_ref[t]
        sb = sb_ref[t]

        @pl.when(firstc_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        r = r_ref[0]                    # [bk//128, 128] int32 (global)
        s = s_ref[0]
        g_t = g_ref[:].astype(dt)       # [bn, F]
        h_t = h_ref[:].astype(dt)       # [bs, F]
        rows_r = jax.lax.broadcasted_iota(jnp.int32, (128, bn), 1)
        rows_s = jax.lax.broadcasted_iota(jnp.int32, (128, bs), 1)
        for j in range(r.shape[0]):
            lr = r[j] - rb * bn          # [128]; out-of-range rows -> all-0
            ls = s[j] - sb * bs
            a_oh = (rows_r == lr[:, None]).astype(dt)        # [128, bn]
            b_oh = (rows_s == ls[:, None]).astype(dt)        # [128, bs]
            ge = jnp.dot(a_oh, g_t, preferred_element_type=jnp.float32,
                         precision=prec)                     # [128, F]
            he = jnp.dot(b_oh, h_t, preferred_element_type=jnp.float32,
                         precision=prec)
            o_ref[0, j, :] += jnp.sum(ge * he, axis=-1)

    return body


def cluster_sddmm(
    g: jax.Array,          # [N, F] cotangent rows (receiver side)
    h: jax.Array,          # [N, F] node values (sender side)
    receivers: jax.Array,  # [E] int32 global, sorted by (rb, sb)
    senders: jax.Array,    # [E] int32 global, aligned
    plan: tuple,           # ClusterPlan device arrays (uses first_chunk)
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> jax.Array:
    """Sampled dense-dense matmul on the cluster layout:
    ``out[e] = <g[receivers_e], h[senders_e]>`` — the attention dw
    backward — computed per (rb, sb) pair from VMEM-resident tiles (two
    one-hot MXU matmuls + a row reduce per 128-edge sub-chunk) instead of
    two [E, F] HBM gathers.  Output is edge-aligned, padded to a ``bk``
    multiple (padding lanes read 0).  Twin/oracle: the gathered row dot.

    An edge appears in exactly one (rb, sb) pair; a visiting pair that
    does not own a lane's edge contributes 0 there (its one-hot row is
    empty), so boundary-chunk accumulation across consecutive pairs is
    exact.  bf16 inputs take the fast MXU mode: each one-hot matmul is a
    pure row pick (single-term sums, exact in bf16) and the dot-product
    reduce accumulates f32.
    """
    m = S.mode()
    e = receivers.shape[0]
    e_pad = S.round_up(max(e, 1), bk)
    if m == "xla" or e == 0:
        if e == 0:
            return jnp.zeros((e_pad,), jnp.float32)
        acc = jnp.sum(g[receivers].astype(jnp.float32)
                      * h[senders].astype(jnp.float32), axis=-1)
        return jnp.pad(acc, (0, e_pad - e))
    f = h.shape[-1]
    fp = S.round_up(f, 128)
    n_pad = S.round_up(num_nodes, max(bn, bs))
    g_p = S.pad_axis(S.pad_axis(g, -1, 128), 0, max(bn, bs))
    h_p = S.pad_axis(S.pad_axis(h, -1, 128), 0, max(bn, bs))
    pad_ids = lambda a: jnp.pad(a, (0, e_pad - e), constant_values=n_pad)
    r2d = pad_ids(receivers).reshape(e_pad // bk, bk // 128, 128)
    s2d = pad_ids(senders).reshape(e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    fast_bf16 = (h.dtype == jnp.bfloat16 and g.dtype == jnp.bfloat16)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, fc: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, fc: (chk[t], 0, 0)),
            pl.BlockSpec((bn, fp), lambda t, rb, sb, chk, fc: (rb[t], 0)),
            pl.BlockSpec((bs, fp), lambda t, rb, sb, chk, fc: (sb[t], 0)),
        ],
        out_specs=pl.BlockSpec((1, bk // 128, 128),
                               lambda t, rb, sb, chk, fc: (chk[t], 0, 0)),
    )
    out = pl.pallas_call(
        _sddmm_body(bn, bs, fast_bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e_pad // bk, bk // 128, 128),
                                       jnp.float32),
        interpret=S.interpret_flag(m),
    )(plan[0], plan[1], plan[2], plan[4], r2d, s2d, g_p, h_p)
    return out.reshape(e_pad)


# --- host-side split: clustered pairs vs stragglers ---------------------------


class ClusterSplit(NamedTuple):
    """Host result of :func:`build_cluster_split` (numpy; see to_device).

    Clustered edges (pair density >= threshold) carry a ClusterPlan;
    stragglers keep the receiver-sorted layout + block-CSR plan of the
    main path.  ``w_*`` are the static mean-aggregation weights of each
    edge and of its reverse (1/deg of the opposite endpoint) — the
    involution backward needs no index lookup (same trick as
    parallel/node_shard.py).

    The ``*_map`` fields route RUNTIME per-edge weights (attention) from
    the prepare layout into the two split layouts without a scatter:
    ``w_c = w[c_map]`` etc.  ``c_map_rev = rev_perm[c_map]`` so the
    involution backward's reversed weights are one more static gather.
    ``inv_map`` goes the other way — ``dw[e] =
    concat(dw_c_pad, dw_s, [0])[inv_map[e]]`` reconstitutes a prepare-
    layout per-edge gradient from the two split-layout pieces with a
    gather instead of a scatter.  All maps are None when the split was
    built without ``rev_perm`` (weighted aggregation then unsupported).
    """

    c_recv: np.ndarray   # [Ec] clustered receivers, (rb, sb)-sorted
    c_send: np.ndarray   # [Ec]
    c_wf: np.ndarray     # [Ec] 1/deg[recv]
    c_wb: np.ndarray     # [Ec] 1/deg[send]
    c_plan: ClusterPlan
    s_recv: np.ndarray   # [Es] straggler receivers, ascending
    s_send: np.ndarray   # [Es]
    s_wf: np.ndarray
    s_wb: np.ndarray
    s_plan: tuple        # block-CSR plan for the straggler receivers
    frac_clustered: float
    c_map: np.ndarray | None = None      # [Ec] prepare-layout edge index
    c_map_rev: np.ndarray | None = None  # [Ec] index of the reverse edge
    s_map: np.ndarray | None = None      # [Es] (padding entries -> 0)
    s_map_rev: np.ndarray | None = None  # [Es]
    s_valid: np.ndarray | None = None    # [Es] f32 1 on real stragglers
    inv_map: np.ndarray | None = None    # [E] -> slot in the dw concat
    # the clustered-dw slot count inv_map was built against; the dw
    # backward pads/slices cluster_sddmm's output to THIS length so a
    # split built with a non-default bk can never misalign the concat
    ec_pad: int = 0


def build_cluster_split(
    senders: np.ndarray,
    receivers: np.ndarray,  # ascending (prepare layout)
    edge_mask: np.ndarray,
    deg: np.ndarray,
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
    min_pair_edges: int = 256,
    rev_perm: np.ndarray | None = None,
) -> ClusterSplit:
    from hyperspace_tpu.kernels.segment import build_csr_plan

    mask = np.asarray(edge_mask)
    pos = np.flatnonzero(mask)              # prepare-layout index per edge
    r = np.asarray(receivers)[mask]
    s = np.asarray(senders)[mask]
    d = np.maximum(np.asarray(deg), 1.0).astype(np.float32)
    nsb = num_nodes // bs + 1
    key = (r // bn).astype(np.int64) * nsb + s // bs
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    uniq, inv, counts = np.unique(key_s, return_inverse=True,
                                  return_counts=True)
    dense = counts[inv] >= min_pair_edges   # per sorted edge
    c_idx = order[dense]
    s_idx = np.sort(order[~dense])          # back to receiver-ascending
    c_recv, c_send = r[c_idx], s[c_idx]
    s_recv, s_send = r[s_idx], s[s_idx]

    c_plan = build_cluster_plan(c_recv, c_send, num_nodes, bn, bs, bk)
    # straggler CSR plan wants every node block covered; sentinel-pad to
    # keep receivers sorted (padding edges carry w = 0)
    e_s = S.round_up(max(len(s_recv), 1), bk)
    s_recv_p = np.full(e_s, num_nodes - 1, np.int32)
    s_send_p = np.zeros(e_s, np.int32)
    s_wf = np.zeros(e_s, np.float32)
    s_wb = np.zeros(e_s, np.float32)
    s_recv_p[: len(s_recv)] = s_recv
    s_send_p[: len(s_send)] = s_send
    s_wf[: len(s_recv)] = 1.0 / d[s_recv]
    s_wb[: len(s_recv)] = 1.0 / d[s_send]
    s_plan = tuple(build_csr_plan(s_recv_p, num_nodes, bn=128, bk=bk))

    # weighted-aggregation routing maps (module doc); need rev_perm so
    # the backward can gather the reverse edge's weight statically
    maps: dict = {}
    if rev_perm is not None:
        rp = np.asarray(rev_perm)
        c_map = pos[c_idx].astype(np.int32)
        s_map = np.zeros(e_s, np.int32)
        s_map[: len(s_idx)] = pos[s_idx]
        s_valid = np.zeros(e_s, np.float32)
        s_valid[: len(s_idx)] = 1.0
        ec_pad = S.round_up(max(len(c_map), 1), bk)  # kernel output size
        inv_map = np.full(len(mask), ec_pad + e_s, np.int32)  # zero slot
        inv_map[pos[c_idx]] = np.arange(len(c_idx), dtype=np.int32)
        inv_map[pos[s_idx]] = ec_pad + np.arange(len(s_idx), dtype=np.int32)
        maps = dict(
            c_map=c_map, c_map_rev=rp[c_map].astype(np.int32),
            s_map=s_map, s_map_rev=rp[s_map].astype(np.int32) * (
                s_valid > 0),  # padding rows point at edge 0, masked out
            s_valid=s_valid, inv_map=inv_map, ec_pad=int(ec_pad))

    return ClusterSplit(
        c_recv=c_recv.astype(np.int32), c_send=c_send.astype(np.int32),
        c_wf=(1.0 / d[c_recv]), c_wb=(1.0 / d[c_send]),
        c_plan=c_plan,
        s_recv=s_recv_p, s_send=s_send_p, s_wf=s_wf, s_wb=s_wb,
        s_plan=s_plan,
        frac_clustered=float(len(c_recv)) / max(len(r), 1),
        **maps,
    )
