"""Cluster-pair SpMM aggregation — kill the [E, F] message round-trip.

The r02 anatomy probes (docs/benchmarks.md) showed the aggregation's
gather (`w·h[senders]`) is latency-bound and the block-CSR scatter reads
the materialized [E, F] messages back from HBM: every pass pays ~2·E·F
bytes of HBM traffic that exists only because the gather and the scatter
are separate XLA/Pallas ops.

This kernel processes edges grouped by (receiver-block, sender-block)
pairs and never materializes messages: with both endpoint blocks resident
in VMEM, a 128-edge sub-chunk becomes two MXU matmuls

    out_tile  +=  A @ (B @ h_tile)
    A[r_loc, e] = w_e      (edge-weighted receiver one-hot, [bn, 128])
    B[e, s_loc] = 1        (sender one-hot, [128, bs])

so HBM traffic is one h-tile load per (rb, sb) pair plus the edge id/
weight stream — for edges with block locality that is a fraction of
E·F.  Low-density pairs would waste a whole tile load on a few edges, so
the host splitter (`build_cluster_split`) routes only pairs with
``>= min_pair_edges`` through this kernel; the rest ("stragglers") keep
the existing gather + block-CSR path.  For a symmetrized edge list the
pair (a, b) and its mirror (b, a) have equal edge counts, so the split
is closed under edge reversal and the involution backward
(nn/scatter.py) survives on both paths.

Exactness: B@h is a pure row selection (each edge row has exactly one 1,
so no two nonzeros ever sum) — in bf16 the products and single-term sums
are exact, which is why the bf16 path can use the fast single-pass MXU
mode; accumulation is f32 throughout.  f32 inputs use HIGHEST precision
like kernels/segment.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S

_BN = 256   # receiver-block rows (output tile)
_BS = 256   # sender-block rows (h tile).  bs=128/thr=64 wins the
# ISOLATED forward aggregation (24.1 vs 29.4 ms — smaller tiles make
# ~200-edge pairs profitable) but LOSES the full train step (0.146 vs
# 0.136 s clean-chip): in the full step XLA overlaps the straggler
# gather chain with other work, so shrinking it saves nothing while the
# larger cluster grid adds serial time.  Full-step wins set the default.
_BK = 512   # edges per chunk


class ClusterPlan(NamedTuple):
    """Work-item schedule for :func:`cluster_aggregate` (host-built).

    Items are receiver-block-major; ``first`` marks each rb's first item
    (the kernel zeroes the output tile there).  Every receiver block gets
    at least one item even if it owns no clustered edge.
    """

    rb: np.ndarray     # [T] item -> receiver-block index
    sb: np.ndarray     # [T] item -> sender-block index
    chunk: np.ndarray  # [T] item -> edge-chunk index
    first: np.ndarray  # [T] 1 iff first item of its receiver block


def build_cluster_plan(
    receivers: np.ndarray,  # [E] sorted by (rb, sb) within the clustered set
    senders: np.ndarray,    # [E] aligned
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> ClusterPlan:
    """Plan (rb, sb, chunk) items over edges pre-sorted by (rb, sb).

    Boundary chunks shared by two pairs are loaded by both and masked by
    the in-kernel local-range test (same trick as kernels/segment.py).
    """
    r = np.asarray(receivers)
    s = np.asarray(senders)
    e_pad = S.round_up(max(len(r), 1), bk)
    nchunks = e_pad // bk
    nb = -(-num_nodes // bn)
    key = (r // bn).astype(np.int64) * ((num_nodes // bs) + 1) + s // bs
    if len(key) > 1 and not np.all(np.diff(key) >= 0):
        raise ValueError("cluster plan needs edges sorted by (rb, sb)")
    # pair boundaries
    starts = np.flatnonzero(np.r_[True, key[1:] != key[:-1]]) if len(key) else np.zeros(0, np.int64)
    ends = np.r_[starts[1:], len(key)] if len(starts) else starts
    p_rb = (r[starts] // bn).astype(np.int32) if len(starts) else np.zeros(0, np.int32)
    p_sb = (s[starts] // bs).astype(np.int32) if len(starts) else np.zeros(0, np.int32)
    c0 = np.minimum(starts // bk, nchunks - 1)
    c1 = np.clip(-(-ends // bk), c0 + 1, nchunks)
    counts = (c1 - c0).astype(np.int64)

    rb_items = np.repeat(p_rb, counts)
    sb_items = np.repeat(p_sb, counts)
    chunk_items = (np.arange(counts.sum(), dtype=np.int64)
                   - np.repeat(np.cumsum(counts) - counts, counts)
                   + np.repeat(c0, counts)).astype(np.int32)

    # every receiver block needs >= 1 item so its output tile is zeroed;
    # dummy items point at chunk 0 whose edges (some other pair's) fail
    # the local-range test and contribute nothing
    present = np.zeros(nb, bool)
    present[p_rb] = True
    missing = np.flatnonzero(~present).astype(np.int32)
    rb_items = np.concatenate([rb_items, missing])
    sb_items = np.concatenate([sb_items, np.zeros(len(missing), np.int32)])
    chunk_items = np.concatenate([chunk_items, np.zeros(len(missing), np.int32)])

    order = np.argsort(rb_items, kind="stable")
    rb_items = rb_items[order].astype(np.int32)
    sb_items = sb_items[order].astype(np.int32)
    chunk_items = chunk_items[order].astype(np.int32)
    first = np.zeros(len(rb_items), np.int32)
    first[np.flatnonzero(np.r_[True, rb_items[1:] != rb_items[:-1]])] = 1
    return ClusterPlan(rb_items, sb_items, chunk_items, first)


def _body(bn: int, bs: int, fast_bf16: bool):
    prec = None if fast_bf16 else jax.lax.Precision.HIGHEST
    dt = jnp.bfloat16 if fast_bf16 else jnp.float32

    def body(rb_ref, sb_ref, chk_ref, first_ref, r_ref, s_ref, w_ref,
             h_ref, o_ref):
        t = pl.program_id(0)
        rb = rb_ref[t]
        sb = sb_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        r = r_ref[0]                    # [bk//128, 128] int32 (global)
        s = s_ref[0]
        w = w_ref[0].astype(jnp.float32)
        h_t = h_ref[:].astype(dt)       # [bs, F]
        acc = jnp.zeros_like(o_ref[:], jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (128, bs), 1)
        for j in range(r.shape[0]):
            ls = s[j] - sb * bs          # [128]; out-of-range matches nothing
            lr = r[j] - rb * bn
            b_oh = (cols == ls[:, None]).astype(dt)          # [128, bs]
            tmp = jnp.dot(b_oh, h_t, preferred_element_type=jnp.float32,
                          precision=prec)                    # [128, F] exact
            a_w = jnp.where(rows == lr[None, :], w[j][None, :], 0.0)
            acc += jnp.dot(a_w.astype(dt), tmp.astype(dt),
                           preferred_element_type=jnp.float32, precision=prec)
        o_ref[:] += acc

    return body


def cluster_aggregate(
    h: jax.Array,          # [N, F] node values
    w: jax.Array,          # [E] edge weights (0 on padding/masked edges)
    receivers: jax.Array,  # [E] int32 global, sorted by (rb, sb)
    senders: jax.Array,    # [E] int32 global, aligned
    plan: tuple,           # ClusterPlan device arrays (rb, sb, chunk, first)
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e] without ever
    materializing [E, F] messages.  Twin/oracle: ``segment_sum`` of the
    gathered messages (any receiver order)."""
    m = S.mode()
    if m == "xla":
        acc_dt = jnp.promote_types(h.dtype, jnp.float32)
        msgs = (w[:, None] * h[senders]).astype(acc_dt)
        return jax.ops.segment_sum(msgs, receivers, num_nodes).astype(h.dtype)
    e = receivers.shape[0]
    if e == 0:
        # an empty clustered set still carries one dummy plan item per
        # receiver block; skipping the kernel (sum of nothing = 0) avoids
        # indexing chunk 0 of a zero-chunk edge array
        return jnp.zeros((num_nodes, h.shape[-1]), h.dtype)
    f = h.shape[-1]
    fp = S.round_up(f, 128)
    n_pad = S.round_up(num_nodes, max(bn, bs))
    h_p = S.pad_axis(S.pad_axis(h, -1, 128), 0, max(bn, bs))
    e_pad = S.round_up(e, bk)
    # pad ids out-of-range so padded lanes match no local row
    pad_ids = lambda a: jnp.pad(a, (0, e_pad - e), constant_values=n_pad)
    r2d = pad_ids(receivers).reshape(e_pad // bk, bk // 128, 128)
    s2d = pad_ids(senders).reshape(e_pad // bk, bk // 128, 128)
    w2d = jnp.pad(w.astype(jnp.float32), (0, e_pad - e)).reshape(
        e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    fast_bf16 = h.dtype == jnp.bfloat16
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((1, bk // 128, 128),
                         lambda t, rb, sb, chk, first: (chk[t], 0, 0)),
            pl.BlockSpec((bs, fp), lambda t, rb, sb, chk, first: (sb[t], 0)),
        ],
        out_specs=pl.BlockSpec((bn, fp),
                               lambda t, rb, sb, chk, first: (rb[t], 0)),
    )
    out = pl.pallas_call(
        _body(bn, bs, fast_bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S.round_up(n_pad, bn), fp),
                                       jnp.float32),
        interpret=S.interpret_flag(m),
    )(*tuple(plan)[:4], r2d, s2d, w2d, h_p)
    return out[:num_nodes, :f].astype(h.dtype)


# --- fused in-tile attention: logits computed from VMEM-resident blocks -------
#
# r04 measured the attention step's cost to be the COUNT of [E]-length
# HBM passes (~10–28 ms per 2.4 M-row pass, width-independent), and the
# r04 weighted cluster path was a wash precisely because routing runtime
# weights into the cluster layout added [E] gathers back.  The r05 fix:
# never materialize clustered-edge weights at all.  With both endpoint
# blocks resident in VMEM, the GAT logit α_s[s_e] + α_r[r_e] is two
# masked one-hot picks from [bs]/[bn] score vectors, the bounded-logit
# softmax weight exp(B·tanh(leaky(·)/B)) is VPU math, and the weighted
# aggregation is the same two-matmul program as the mean kernel — so
# clustered edges never touch the [E] stream in EITHER direction.  The
# forward emits unnormalized [num | den] partials ([N, F+1]); the
# straggler edges run the planned fused path and the division happens
# once on the combined [N, F+1] (nn/scatter.cluster_att_partial).
#
# The backward is one kernel producing dh AND both score gradients, all
# receiver-block-indexed via the edge involution (the clustered set is
# reversal-closed):
#
#   dh[i]   = Σ_{e: r_e=i} w_rev(e) · d_num[s_e]
#   dα_r[i] = Σ_{e: r_e=i} dpre_e
#   dα_s[i] = Σ_{e: r_e=i} dpre_rev(e)
#
# with w_rev(e) = f(α_s[r_e] + α_r[s_e]) (the reverse edge's weight —
# both alphas resident), dw_e = <d_num[r_e], h[s_e]> + d_den[r_e], and
# dpre = dw · w · f'(pre).  No [E]-aligned array exists anywhere.


def _att_squash(pre, bound, slope):
    """bounded_att_logits + its derivative, shared by both kernel bodies
    (mirrors nn.gcn.bounded_att_logits exactly)."""
    lam = jnp.where(pre >= 0, pre, slope * pre)
    th = jnp.tanh(lam / bound)
    w = jnp.exp(bound * th)
    dpre_factor = w * (1.0 - th * th) * jnp.where(pre >= 0, 1.0, slope)
    return w, dpre_factor


def _pick_grouped(vec_t, idx):
    """Per-edge pick from a resident score tile in its native layout.

    ``vec_t`` is [G, 128] f32 (a length-G·128 vector as loaded from its
    (1, G, 128) block), ``idx`` is [128] int32 local indices; returns the
    [128] picked values, 0 where idx is out of [0, G·128) — masked
    one-hot reduces only, no cross-lane reshape (Mosaic-safe).
    """
    g_idx = idx // 128
    l_idx = idx % 128
    rows = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
    sel = rows == l_idx[None, :]
    out = jnp.zeros((128,), jnp.float32)
    for g in range(vec_t.shape[0]):
        v = jnp.sum(jnp.where(sel, vec_t[g][:, None], 0.0), axis=0)
        out = out + jnp.where(g_idx == g, v, 0.0)
    return out


def _att_fwd_body(bn, bs, f, fp, fp_ext, fast_bf16, bound, slope):
    prec = None if fast_bf16 else jax.lax.Precision.HIGHEST
    dt = jnp.bfloat16 if fast_bf16 else jnp.float32

    def body(rb_ref, sb_ref, chk_ref, first_ref, r_ref, s_ref, h_ref,
             as_ref, ar_ref, o_ref):
        t = pl.program_id(0)
        rb = rb_ref[t]
        sb = sb_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        r = r_ref[0]                       # [bk//128, 128] int32 (global)
        s = s_ref[0]
        h_t = h_ref[:].astype(dt)          # [bs, fp]
        a_s_t = as_ref[0]                  # [bs//128, 128] f32 (senders)
        a_r_t = ar_ref[0]                  # [bn//128, 128] f32 (receivers)
        acc = jnp.zeros((bn, fp_ext), jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (128, bs), 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, fp_ext), 1)
        for j in range(r.shape[0]):
            ls = s[j] - sb * bs            # [128]; out-of-range matches 0
            lr = r[j] - rb * bn
            sel_r = rows == lr[None, :]    # [bn, 128]
            b_oh = (cols == ls[:, None]).astype(dt)      # [128, bs]
            # the in-tile logit: two masked picks + VPU squash (no [E]
            # stream); out-of-pair lanes (boundary chunks, padding ids)
            # are killed by the ls validity mask — sel_r alone would let
            # a same-rb neighbor pair's edge leak into the denominator
            pre = _pick_grouped(a_s_t, ls) + _pick_grouped(a_r_t, lr)
            w, _ = _att_squash(pre, bound, slope)
            w = jnp.where((ls >= 0) & (ls < bs), w, 0.0)
            tmp = jnp.dot(b_oh, h_t,                     # [128, fp] picks
                          preferred_element_type=jnp.float32,
                          precision=prec)
            # num|den ride one matmul: a constant-1 column at lane f
            if fp_ext > fp:
                extra = (jax.lax.broadcasted_iota(
                    jnp.int32, (128, fp_ext - fp), 1) == (f - fp)
                ).astype(jnp.float32)
                tmp_ext = jnp.concatenate([tmp, extra], axis=1)
            else:                          # h padding lanes are 0 -> safe
                tmp_ext = tmp + (lane == f).astype(jnp.float32)
            a_w = jnp.where(sel_r, w[None, :], 0.0)      # [bn, 128]
            acc += jnp.dot(a_w.astype(dt), tmp_ext.astype(dt),
                           preferred_element_type=jnp.float32,
                           precision=prec)
        o_ref[:] += acc

    return body


def cluster_att_fwd(
    h: jax.Array,          # [N, F] node values (agg dtype; bf16 = fast path)
    alpha_s: jax.Array,    # [N] sender attention scores
    alpha_r: jax.Array,    # [N] receiver attention scores
    receivers: jax.Array,  # [E] int32 global, sorted by (rb, sb)
    senders: jax.Array,    # [E] int32 global, aligned
    plan: tuple,           # ClusterPlan device arrays
    num_nodes: int,
    negative_slope: float = 0.2,
    bound: float = 30.0,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
) -> jax.Array:
    """[N, F+1] f32 unnormalized attention partials over the clustered
    edges: ``out[r] = Σ_e w_e·[h[s_e] | 1]`` with
    ``w_e = exp(bounded_att_logits(α_s[s_e]+α_r[r_e]))`` computed
    IN-TILE.  Twin/oracle: exp/mask/segment-sum of the gathered chain.
    """
    f = h.shape[-1]
    m = S.mode()
    e = receivers.shape[0]
    if m == "xla" or e == 0:
        if e == 0:
            return jnp.zeros((num_nodes, f + 1), jnp.float32)
        pre = (alpha_s.astype(jnp.float32)[senders]
               + alpha_r.astype(jnp.float32)[receivers])
        w, _ = _att_squash(pre, bound, negative_slope)
        w = w.astype(h.dtype).astype(jnp.float32)  # match kernel rounding
        msgs = jnp.concatenate(
            [w[:, None] * h[senders].astype(jnp.float32), w[:, None]],
            axis=1)
        return jax.ops.segment_sum(msgs, receivers, num_nodes)
    fp = S.round_up(f, 128)
    fp_ext = S.round_up(f + 1, 128)
    n_pad = S.round_up(num_nodes, max(bn, bs))
    h_p = S.pad_axis(S.pad_axis(h, -1, 128), 0, max(bn, bs))
    a_s2 = jnp.pad(alpha_s.astype(jnp.float32),
                   (0, n_pad - num_nodes)).reshape(n_pad // bs,
                                                   bs // 128, 128)
    a_r2 = jnp.pad(alpha_r.astype(jnp.float32),
                   (0, n_pad - num_nodes)).reshape(n_pad // bn,
                                                   bn // 128, 128)
    e_pad = S.round_up(e, bk)
    pad_ids = lambda a: jnp.pad(a, (0, e_pad - e), constant_values=n_pad)
    r2d = pad_ids(receivers).reshape(e_pad // bk, bk // 128, 128)
    s2d = pad_ids(senders).reshape(e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    fast_bf16 = h.dtype == jnp.bfloat16
    chunk_spec = pl.BlockSpec((1, bk // 128, 128),
                              lambda t, rb, sb, chk, first: (chk[t], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            chunk_spec, chunk_spec,
            pl.BlockSpec((bs, fp), lambda t, rb, sb, chk, first: (sb[t], 0)),
            pl.BlockSpec((1, bs // 128, 128),
                         lambda t, rb, sb, chk, first: (sb[t], 0, 0)),
            pl.BlockSpec((1, bn // 128, 128),
                         lambda t, rb, sb, chk, first: (rb[t], 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, fp_ext),
                               lambda t, rb, sb, chk, first: (rb[t], 0)),
    )
    out = pl.pallas_call(
        _att_fwd_body(bn, bs, f, fp, fp_ext, fast_bf16,
                      float(bound), float(negative_slope)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S.round_up(n_pad, bn), fp_ext),
                                       jnp.float32),
        interpret=S.interpret_flag(m),
    )(*tuple(plan)[:4], r2d, s2d, h_p, a_s2, a_r2)
    return out[:num_nodes, : f + 1]


def _att_bwd_body(bn, bs, f, fp, fp_ext, fp_out, fast_bf16, bound, slope):
    prec = None if fast_bf16 else jax.lax.Precision.HIGHEST
    dt = jnp.bfloat16 if fast_bf16 else jnp.float32

    def body(rb_ref, sb_ref, chk_ref, first_ref, r_ref, s_ref,
             g_rb_ref, g_sb_ref, h_rb_ref, h_sb_ref,
             as_rb_ref, as_sb_ref, ar_rb_ref, ar_sb_ref, o_ref):
        t = pl.program_id(0)
        rb = rb_ref[t]
        sb = sb_ref[t]

        @pl.when(first_ref[t] == 1)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        r = r_ref[0]
        s = s_ref[0]
        g_rb = g_rb_ref[:].astype(dt)        # [bn, fp_ext] d_num|d_den
        g_sb = g_sb_ref[:].astype(dt)        # [bs, fp_ext]
        h_rb = h_rb_ref[:].astype(dt)        # [bn, fp]
        h_sb = h_sb_ref[:].astype(dt)        # [bs, fp]
        a_s_rb = as_rb_ref[0]                # [bn//128, 128] f32
        a_s_sb = as_sb_ref[0]                # [bs//128, 128]
        a_r_rb = ar_rb_ref[0]
        a_r_sb = ar_sb_ref[0]
        acc = jnp.zeros((bn, fp_out), jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 128), 0)
        cols_s = jax.lax.broadcasted_iota(jnp.int32, (128, bs), 1)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (128, bn), 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, fp_out), 1)
        num_lanes = (jax.lax.broadcasted_iota(jnp.int32, (1, fp), 1)
                     < f).astype(jnp.float32)
        for j in range(r.shape[0]):
            ls = s[j] - sb * bs
            lr = r[j] - rb * bn
            sel_r = rows == lr[None, :]      # [bn, 128]
            valid = ((ls >= 0) & (ls < bs) & (lr >= 0) & (lr < bn)
                     ).astype(jnp.float32)
            b_oh = (cols_s == ls[:, None]).astype(dt)   # [128, bs]
            r_oh = (cols_r == lr[:, None]).astype(dt)   # [128, bn]
            gs = jnp.dot(b_oh, g_sb, preferred_element_type=jnp.float32,
                         precision=prec)     # [128, fp_ext]  rows d[s_e]
            gr = jnp.dot(r_oh, g_rb, preferred_element_type=jnp.float32,
                         precision=prec)     # [128, fp_ext]  rows d[r_e]
            hs = jnp.dot(b_oh, h_sb, preferred_element_type=jnp.float32,
                         precision=prec)     # [128, fp]      rows h[s_e]
            hr = jnp.dot(r_oh, h_rb, preferred_element_type=jnp.float32,
                         precision=prec)     # [128, fp]      rows h[r_e]
            # dw_e = <d_num[r_e], h[s_e]> + d_den[r_e]; the h padding
            # lanes are 0, so full-width products exclude lane f safely
            dw = jnp.sum(gr[:, :fp] * hs, axis=1) + gr[:, f]
            dw_rev = jnp.sum(gs[:, :fp] * hr, axis=1) + gs[:, f]
            pre = _pick_grouped(a_s_sb, ls) + _pick_grouped(a_r_rb, lr)
            pre_rev = (_pick_grouped(a_s_rb, lr)
                       + _pick_grouped(a_r_sb, ls))
            w, dfac = _att_squash(pre, bound, slope)
            w_rev, dfac_rev = _att_squash(pre_rev, bound, slope)
            dpre = dw * dfac * valid
            dpre_rev = dw_rev * dfac_rev * valid
            # dh[r] += w_rev · d_num[s]: mask d_den out of the gs rows,
            # keep only the first f lanes live
            gs_num = gs[:, :fp] * num_lanes
            if fp_out > fp:
                gs_num = jnp.concatenate(
                    [gs_num, jnp.zeros((128, fp_out - fp), jnp.float32)],
                    axis=1)
            a_w_rev = jnp.where(sel_r, (w_rev * valid)[None, :], 0.0)
            acc += jnp.dot(a_w_rev.astype(dt), gs_num.astype(dt),
                           preferred_element_type=jnp.float32,
                           precision=prec)
            # score gradients ride lanes f (dα_r) and f+1 (dα_s)
            da_r = jnp.sum(jnp.where(sel_r, dpre[None, :], 0.0), axis=1)
            da_s = jnp.sum(jnp.where(sel_r, dpre_rev[None, :], 0.0),
                           axis=1)
            acc += (da_r[:, None] * (lane == f)
                    + da_s[:, None] * (lane == f + 1))
        o_ref[:] += acc

    return body


def cluster_att_bwd(
    g_ext: jax.Array,      # [N, F+1] f32 cotangent (d_num | d_den)
    h: jax.Array,          # [N, F] node values (same array as forward)
    alpha_s: jax.Array,    # [N]
    alpha_r: jax.Array,    # [N]
    receivers: jax.Array,  # [E] int32 global, sorted by (rb, sb)
    senders: jax.Array,    # [E]
    plan: tuple,
    num_nodes: int,
    negative_slope: float = 0.2,
    bound: float = 30.0,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
):
    """Backward of :func:`cluster_att_fwd`: returns
    ``(dh [N, F] f32, d_alpha_s [N] f32, d_alpha_r [N] f32)`` — one
    kernel, everything receiver-block-indexed via the edge involution
    (module comment above).  Twin/oracle: jax.vjp of the gathered chain.
    """
    f = h.shape[-1]
    m = S.mode()
    e = receivers.shape[0]
    if m == "xla" or e == 0:
        if e == 0:
            z = jnp.zeros((num_nodes,), jnp.float32)
            return jnp.zeros((num_nodes, f), jnp.float32), z, z

        def fwd(hh, a_s, a_r):
            pre = a_s[senders] + a_r[receivers]
            w, _ = _att_squash(pre, bound, negative_slope)
            w = w.astype(hh.dtype).astype(jnp.float32)
            msgs = jnp.concatenate(
                [w[:, None] * hh.astype(jnp.float32)[senders], w[:, None]],
                axis=1)
            return jax.ops.segment_sum(msgs, receivers, num_nodes)

        _, vjp = jax.vjp(fwd, h, alpha_s.astype(jnp.float32),
                         alpha_r.astype(jnp.float32))
        dh, da_s, da_r = vjp(g_ext.astype(jnp.float32))
        return dh.astype(jnp.float32), da_s, da_r
    fp = S.round_up(f, 128)
    fp_ext = S.round_up(f + 1, 128)
    fp_out = S.round_up(f + 2, 128)
    n_pad = S.round_up(num_nodes, max(bn, bs))
    g_p = S.pad_axis(S.pad_axis(g_ext.astype(jnp.float32), -1, 128),
                     0, max(bn, bs))
    h_p = S.pad_axis(S.pad_axis(h, -1, 128), 0, max(bn, bs))
    a_pad = lambda a: jnp.pad(a.astype(jnp.float32), (0, n_pad - num_nodes))
    a_s_sb = a_pad(alpha_s).reshape(n_pad // bs, bs // 128, 128)
    a_s_rb = a_pad(alpha_s).reshape(n_pad // bn, bn // 128, 128)
    a_r_sb = a_pad(alpha_r).reshape(n_pad // bs, bs // 128, 128)
    a_r_rb = a_pad(alpha_r).reshape(n_pad // bn, bn // 128, 128)
    e_pad = S.round_up(e, bk)
    pad_ids = lambda a: jnp.pad(a, (0, e_pad - e), constant_values=n_pad)
    r2d = pad_ids(receivers).reshape(e_pad // bk, bk // 128, 128)
    s2d = pad_ids(senders).reshape(e_pad // bk, bk // 128, 128)
    t = plan[0].shape[0]
    fast_bf16 = h.dtype == jnp.bfloat16
    chunk_spec = pl.BlockSpec((1, bk // 128, 128),
                              lambda t, rb, sb, chk, first: (chk[t], 0, 0))
    rb_spec = lambda w_: pl.BlockSpec(
        (bn, w_), lambda t, rb, sb, chk, first: (rb[t], 0))
    sb_spec = lambda w_: pl.BlockSpec(
        (bs, w_), lambda t, rb, sb, chk, first: (sb[t], 0))
    vec_rb = pl.BlockSpec((1, bn // 128, 128),
                          lambda t, rb, sb, chk, first: (rb[t], 0, 0))
    vec_sb = pl.BlockSpec((1, bs // 128, 128),
                          lambda t, rb, sb, chk, first: (sb[t], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(t,),
        in_specs=[
            chunk_spec, chunk_spec,
            rb_spec(fp_ext), sb_spec(fp_ext),      # g at rb, sb
            rb_spec(fp), sb_spec(fp),              # h at rb, sb
            vec_rb, vec_sb,                        # alpha_s at rb, sb
            vec_rb, vec_sb,                        # alpha_r at rb, sb
        ],
        out_specs=pl.BlockSpec((bn, fp_out),
                               lambda t, rb, sb, chk, first: (rb[t], 0)),
    )
    out = pl.pallas_call(
        _att_bwd_body(bn, bs, f, fp, fp_ext, fp_out, fast_bf16,
                      float(bound), float(negative_slope)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S.round_up(n_pad, bn), fp_out),
                                       jnp.float32),
        interpret=S.interpret_flag(m),
    )(*tuple(plan)[:4], r2d, s2d, g_p, g_p, h_p, h_p,
      a_s_rb, a_s_sb, a_r_rb, a_r_sb)
    return (out[:num_nodes, :f], out[:num_nodes, f + 1],
            out[:num_nodes, f])


# --- host-side split: clustered pairs vs stragglers ---------------------------


class ClusterSplit(NamedTuple):
    """Host result of :func:`build_cluster_split` (numpy; see to_device).

    Clustered edges (pair density >= threshold) carry a ClusterPlan;
    stragglers keep the receiver-sorted layout + block-CSR plan of the
    main path.  ``w_*`` are the static mean-aggregation weights of each
    edge and of its reverse (1/deg of the opposite endpoint) — the
    involution backward needs no index lookup (same trick as
    parallel/node_shard.py).

    For attention (nn/scatter.cluster_att_partial) the clustered edges
    run the in-tile kernels above (which need nothing beyond the ids),
    and the STRAGGLER edges run the planned fused attention path on
    their own layout — which needs a self-contained edge involution:
    ``s_rev_local[i]`` is the straggler-array position of edge i's
    reverse (the straggler set is reversal-closed because pair (a, b)
    and its mirror (b, a) always share a density class; padding rows map
    to themselves).  ``s_mask`` is the bool validity mask of the padded
    straggler rows.  Both are None when the split was built without
    ``rev_perm`` (attention-on-cluster then unsupported).
    """

    c_recv: np.ndarray   # [Ec] clustered receivers, (rb, sb)-sorted
    c_send: np.ndarray   # [Ec]
    c_wf: np.ndarray     # [Ec] 1/deg[recv]
    c_wb: np.ndarray     # [Ec] 1/deg[send]
    c_plan: ClusterPlan
    s_recv: np.ndarray   # [Es] straggler receivers, ascending
    s_send: np.ndarray   # [Es]
    s_wf: np.ndarray
    s_wb: np.ndarray
    s_plan: tuple        # block-CSR plan for the straggler receivers
    frac_clustered: float
    s_rev_local: np.ndarray | None = None  # [Es] straggler involution
    s_mask: np.ndarray | None = None       # [Es] bool, 1 on real rows


def build_cluster_split(
    senders: np.ndarray,
    receivers: np.ndarray,  # ascending (prepare layout)
    edge_mask: np.ndarray,
    deg: np.ndarray,
    num_nodes: int,
    bn: int = _BN,
    bs: int = _BS,
    bk: int = _BK,
    min_pair_edges: int = 256,
    rev_perm: np.ndarray | None = None,
) -> ClusterSplit:
    if bn != bs:
        # the straggler/clustered partition is closed under edge reversal
        # ONLY when receivers and senders use identical blockings: edge
        # (a, b) lands in pair (a//bn, b//bs) and its mirror (b, a) in
        # (b//bn, a//bs), which are each other's transposes — hence the
        # same edge count / density class — iff bn == bs.  The attention
        # backward's involution identities (s_rev_local, cluster_att_bwd)
        # require that closure; with bn != bs it fails as an
        # AssertionError deep inside prepare(), so reject up front.
        raise ValueError(
            f"build_cluster_split requires bn == bs (got bn={bn}, "
            f"bs={bs}): reversal closure of the clustered/straggler "
            "split — and with it the attention path's straggler "
            "involution — only holds under identical receiver/sender "
            "blockings")
    from hyperspace_tpu.kernels.segment import build_csr_plan

    mask = np.asarray(edge_mask)
    pos = np.flatnonzero(mask)              # prepare-layout index per edge
    r = np.asarray(receivers)[mask]
    s = np.asarray(senders)[mask]
    d = np.maximum(np.asarray(deg), 1.0).astype(np.float32)
    nsb = num_nodes // bs + 1
    key = (r // bn).astype(np.int64) * nsb + s // bs
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    uniq, inv, counts = np.unique(key_s, return_inverse=True,
                                  return_counts=True)
    dense = counts[inv] >= min_pair_edges   # per sorted edge
    c_idx = order[dense]
    s_idx = np.sort(order[~dense])          # back to receiver-ascending
    c_recv, c_send = r[c_idx], s[c_idx]
    s_recv, s_send = r[s_idx], s[s_idx]

    c_plan = build_cluster_plan(c_recv, c_send, num_nodes, bn, bs, bk)
    # straggler CSR plan wants every node block covered; sentinel-pad to
    # keep receivers sorted (padding edges carry w = 0)
    e_s = S.round_up(max(len(s_recv), 1), bk)
    s_recv_p = np.full(e_s, num_nodes - 1, np.int32)
    s_send_p = np.zeros(e_s, np.int32)
    s_wf = np.zeros(e_s, np.float32)
    s_wb = np.zeros(e_s, np.float32)
    s_recv_p[: len(s_recv)] = s_recv
    s_send_p[: len(s_send)] = s_send
    s_wf[: len(s_recv)] = 1.0 / d[s_recv]
    s_wb[: len(s_recv)] = 1.0 / d[s_send]
    s_plan = tuple(build_csr_plan(s_recv_p, num_nodes, bn=128, bk=bk))

    # straggler-local involution (ClusterSplit doc): lets the planned
    # fused attention path run self-contained on the straggler layout
    maps: dict = {}
    if rev_perm is not None:
        rp = np.asarray(rev_perm)
        loc = np.full(len(mask), -1, np.int64)   # prepare idx -> slot
        loc[pos[s_idx]] = np.arange(len(s_idx))
        s_rev_local = np.arange(e_s, dtype=np.int32)  # padding: self-map
        s_rev_local[: len(s_idx)] = loc[rp[pos[s_idx]]]
        if len(s_idx) and s_rev_local[: len(s_idx)].min() < 0:
            raise AssertionError(
                "straggler set not closed under edge reversal")
        s_mask = np.zeros(e_s, bool)
        s_mask[: len(s_idx)] = True
        maps = dict(s_rev_local=s_rev_local, s_mask=s_mask)

    return ClusterSplit(
        c_recv=c_recv.astype(np.int32), c_send=c_send.astype(np.int32),
        c_wf=(1.0 / d[c_recv]), c_wb=(1.0 / d[c_send]),
        c_plan=c_plan,
        s_recv=s_recv_p, s_send=s_send_p, s_wf=s_wf, s_wb=s_wb,
        s_plan=s_plan,
        frac_clustered=float(len(c_recv)) / max(len(r), 1),
        **maps,
    )
