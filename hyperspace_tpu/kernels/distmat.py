"""Fused pairwise-distance matrix kernels (ball and hyperboloid).

The hot loop of Poincaré-embedding training and of WordNet MAP/mean-rank
eval (SURVEY.md §3.1, §3.5) is an all-pairs hyperbolic distance: every
batch row against every candidate row.  The reference computes this with
its CUDA distance kernels inside autograd [INFERRED]; here it is one
Pallas kernel per (row-block × col-block) output tile built around MXU
matmuls, with **no transposes or 1-D relayouts** — every broadcast of a
per-column quantity is expressed as a rank-1 ``dot_general`` so Mosaic
sees only (sublane, lane)-shaped data.

Math (both forms are the textbook closed expressions, equal to
``PoincareBall.dist`` / ``Lorentz.dist``):

- ball:      d(x,y) = (1/√c)·arcosh(1 + 2c‖x−y‖² / ((1−c‖x‖²)(1−c‖y‖²)))
  with ‖x−y‖² = ‖x‖² − 2⟨x,y⟩ + ‖y‖² — one Gram matmul.
- hyperboloid: d(x,y) = (1/√c)·arcosh(−c⟨x,y⟩_L) — one Minkowski Gram
  matmul (time lane negated).

Gradients flow through the XLA twin (custom_vjp), which is itself a
matmul-shaped expression — fast and fused by XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperspace_tpu.kernels import _support as S
from hyperspace_tpu.manifolds import smath


_dotT = S.dotT


# --- Poincaré ball ------------------------------------------------------------


def _poincare_body(c_ref, x_ref, y_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    gram = _dotT(x, y)                      # [bn, bm]
    xx = S.ksq_norm(x)                      # [bn, 1]
    yy = S.ksq_norm(y)                      # [bm, 1]
    ones = jnp.ones_like(xx)
    yy_t = _dotT(ones, yy)                  # [bn, bm] — rank-1 row broadcast
    d2 = jnp.maximum(xx - 2.0 * gram + yy_t, 0.0)
    den = _dotT(1.0 - c * xx, 1.0 - c * yy)  # (1−c‖x‖²)(1−c‖y‖²), rank-1
    u = 2.0 * c * d2 / jnp.maximum(den, S.EPS_F32)
    dist = S.karcosh1p(u) / jnp.maximum(sc, S.MIN_NORM_F32)
    o_ref[:] = dist.astype(o_ref.dtype)


def _t_poincare_pdist(x, y, c):
    """XLA twin: same closed form, vectorized (== PoincareBall.dist pairwise)."""
    cc = jnp.asarray(c, x.dtype)
    sc = smath.sqrt_c(cc)
    gram = jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    xx = smath.sq_norm(x)          # [n, 1]
    yy = smath.sq_norm(y)[:, 0]    # [m]
    d2 = smath.clamp_min(xx - 2.0 * gram + yy[None, :], 0.0)
    den = smath.clamp_min((1.0 - cc * xx) * (1.0 - cc * yy[None, :]),
                          smath.eps_for(x.dtype))
    u = 2.0 * cc * d2 / den
    return smath.arcosh1p(u) / smath.clamp_min(sc, smath.min_norm(x.dtype))


# --- Lorentz hyperboloid ------------------------------------------------------


def _lorentz_body(c_ref, x_ref, y_ref, o_ref):
    c = c_ref[0, 0]
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    sc = S.ksafe_sqrt(c)
    lane = jax.lax.broadcasted_iota(jnp.int32, y.shape, dimension=1)
    y_flip = jnp.where(lane == 0, -y, y)    # Minkowski signature on the time lane
    gram = _dotT(x, y_flip)                 # ⟨x, y⟩_L
    u = jnp.maximum(-c * gram - 1.0, 0.0)
    dist = S.karcosh1p(u) / jnp.maximum(sc, S.MIN_NORM_F32)
    o_ref[:] = dist.astype(o_ref.dtype)


def _t_lorentz_pdist(x, y, c):
    """XLA twin: arcosh(−c⟨x,y⟩_L)/√c on the full Gram matrix."""
    cc = jnp.asarray(c, x.dtype)
    y_flip = y.at[..., 0].multiply(-1.0)
    gram = jnp.matmul(x, y_flip.T, precision=jax.lax.Precision.HIGHEST)
    u = smath.clamp_min(-cc * gram - 1.0, 0.0)
    return smath.arcosh1p(u) / smath.clamp_min(
        smath.sqrt_c(cc), smath.min_norm(x.dtype))


# --- launcher + public API ----------------------------------------------------


def _launch_pdist(body, x, y, c, mode_):
    n, d = x.shape
    m = y.shape[0]
    bn = min(S.round_up(n, 8), 256)
    bm = min(S.round_up(m, 128), 512)
    # keep x-block + y-block + out-block under the VMEM budget for wide d
    dp_ = S.round_up(d, 128)
    while 4 * (bn * dp_ + bm * dp_ + bn * bm) > S.VMEM_BUDGET and (bn > 8 or bm > 128):
        if bm > 128 and bm >= bn:
            bm = max(128, (bm // 2) // 128 * 128)  # keep 128-lane alignment
        else:
            bn = max(8, (bn // 2) // 8 * 8)
    xp = S.pad_rows_lanes(x, rows_to=bn)
    yp = S.pad_rows_lanes(y, rows_to=bm)
    np_, dp = xp.shape
    mp_ = yp.shape[0]
    grid = (np_ // bn, mp_ // bm)

    out = pl.pallas_call(
        body,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, dp), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, bm), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((np_, mp_), x.dtype),
        interpret=S.interpret_flag(mode_),
    )(S.c_smem(c), xp, yp)
    return out[:n, :m]


def _make_pdist(twin, body):
    def fwd_impl(x, y, c):
        m = S.mode()
        if m == "xla":
            return twin(x, y, c)
        return _launch_pdist(body, x, y, c, m)

    @jax.custom_vjp
    def op(x, y, c):
        return fwd_impl(x, y, c)

    def op_fwd(x, y, c):
        return fwd_impl(x, y, c), (x, y, c)

    def op_bwd(res, g):
        _, vjp = jax.vjp(twin, *res)
        return vjp(g)

    op.defvjp(op_fwd, op_bwd)
    op.__doc__ = twin.__doc__
    return op


poincare_pdist = _make_pdist(_t_poincare_pdist, _poincare_body)
lorentz_pdist = _make_pdist(_t_lorentz_pdist, _lorentz_body)

_PDIST = {"poincare": poincare_pdist, "lorentz": lorentz_pdist}


def pdist(x, y, c, *, manifold: str):
    """All-pairs distance matrix ``d[i, j] = dist(x[i], y[j])`` — the ONE
    public entry point for serving/eval code.

    ``x: [n, d]``, ``y: [m, d]`` (ambient coordinates: Lorentz rows carry
    the time coordinate in lane 0), ``c`` the positive curvature
    magnitude (scalar; may be traced), ``manifold`` one of ``"poincare"``
    / ``"lorentz"``.  Dispatches to the fused Pallas TPU kernel on a TPU
    backend and to the XLA twin (== the closed-form ``PoincareBall.dist``
    / ``Lorentz.dist`` pairwise) elsewhere, per
    ``kernels._support.mode()`` — callers never reach for the ``_t_*``
    twins directly.  Gradients flow through the twin (custom_vjp).
    """
    try:
        op = _PDIST[manifold]
    except KeyError:
        raise ValueError(
            f"pdist: unknown manifold {manifold!r} "
            f"(want one of {sorted(_PDIST)})") from None
    return op(x, y, c)
